"""Checkpoint / resume — crash-safe snapshots with async writeback.

The reference checkpoints by pickling ``Network`` objects (code + weights)
as ``network-snapshot-<kimg>.pkl`` and does NOT save optimizer state —
Adam moments silently reset on resume (SURVEY.md §5 "Checkpoint / resume").
Here the whole ``TrainState`` pytree (params, both Adam states, EMA params,
w_avg, pl_mean, step) round-trips bit-exactly, plus the resolved config
JSON so a checkpoint is self-describing.  ``--resume`` auto-picks the
latest step.

Layout: ``<ckpt_dir>/<step>/state.npz`` — the pytree's leaves in
flatten order (dtype/shape preserved by npz), one directory per step.
Writes are crash-safe by construction: serialize into a dot-prefixed
temp directory on the same filesystem, ``fsync`` the file, then
``os.replace`` the directory into place — a reader (or a ``--resume``
after SIGKILL) can never observe a torn checkpoint, and a failed write
leaves the previous step untouched.

Async writeback (ISSUE 2 tentpole — ``TrainConfig.async_checkpoint``):
``save(..., block=False)`` costs the loop thread O(dispatch) only:

1. a device-side copy of the state (``jnp.copy`` per leaf, async
   dispatch) — required because the step functions DONATE the state
   buffers, so the writer cannot hold references into the live pytree;
2. ``copy_to_host_async`` on every copied leaf — starts the D2H DMA;
3. hand the pytree to a ``SingleSlotWriter`` thread, which settles the
   transfers (``device_get``), serializes, fsyncs, and atomically
   renames.

The writer is single-slot: a second save while one is in flight joins
the first (bounded backpressure, never a pile of host pytrees).  Writer
failures are sticky and re-raised at the loop's next tick boundary via
``check_error``; ``wait`` joins in-flight writes on exit.  Telemetry:
``ckpt/async_inflight`` gauge, ``ckpt/async_writer_heartbeat`` gauge,
``ckpt/async_write_ms`` histogram, ``ckpt/async_total`` /
``ckpt/async_errors_total`` counters, plus the loop-paid ``ckpt/write_ms``
gauge and ``ckpt/save_total`` counter.

Orbax compatibility: directories written by the pre-ISSUE-2 Orbax path
(no ``state.npz``) still restore through an Orbax fallback when the
package is importable; all NEW writes use the self-contained npz format.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gansformer_tpu.core.config import ExperimentConfig
from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.spans import span
from gansformer_tpu.supervise import faults
from gansformer_tpu.train.state import TrainState
from gansformer_tpu.utils.background import SingleSlotWriter

STATE_FILE = "state.npz"

_WRITERS: Dict[str, SingleSlotWriter] = {}

# Serializes the final-directory swap (rename-aside + replace + trash
# cleanup) across threads: the preemption path sync-saves the SAME step
# a timed-out async writer may still be finishing, and two unserialized
# os.replace calls onto one final dir race into ENOTEMPTY.  Only the
# cheap swap serializes — npz serialization stays parallel.
_SWAP_LOCK = threading.Lock()

# Test seam (tests/test_checkpoint_async.py): called with the step number
# after the temp file is fully written, BEFORE the atomic rename — a hook
# that raises models a mid-write crash, and the crash-safety contract is
# that the last good checkpoint must survive it.
_WRITE_HOOK: Optional[Callable[[int], None]] = None

# ONE jitted program copying every leaf (async dispatch, no donation →
# genuinely fresh buffers).  Per-leaf jnp.copy would pay ~a dispatch (and
# a first-call trace) per leaf — measured at >1s of loop-thread time for
# the micro state's ~200 leaves; the fused program is a single dispatch.
_snap_fn = None


def _device_snapshot(leaves):
    global _snap_fn
    if _snap_fn is None:
        _snap_fn = jax.jit(lambda ls: [jnp.copy(l) for l in ls])
    return _snap_fn(leaves)


def _writer(ckpt_dir: str) -> SingleSlotWriter:
    key = os.path.abspath(ckpt_dir)
    if key not in _WRITERS:
        _WRITERS[key] = SingleSlotWriter("ckpt/async")
    return _WRITERS[key]


def _leaf_key(i: int) -> str:
    return f"leaf_{i:05d}"


def _write_state_dir(ckpt_dir: str, step: int, host_leaves: List[np.ndarray],
                     max_to_keep: int) -> None:
    """Serialize → temp dir → fsync → atomic rename.  Any failure cleans
    the temp dir and re-raises; the previous checkpoint is never touched."""
    os.makedirs(ckpt_dir, exist_ok=True)
    # Thread id in the tmp name: the preemption path writes a SYNC save
    # of the current step from the loop thread while a timed-out async
    # writer may still be writing the SAME step from its thread — a
    # pid-only name would interleave two np.savez streams into one file.
    tmp = os.path.join(
        ckpt_dir,
        f".tmp-{step}-{os.getpid()}-{threading.get_ident()}")
    final = os.path.join(ckpt_dir, str(step))
    trash = None
    try:
        os.makedirs(tmp, exist_ok=True)
        path = os.path.join(tmp, STATE_FILE)
        with open(path, "wb") as f:
            np.savez(f, __step=np.int64(step),
                     **{_leaf_key(i): l for i, l in enumerate(host_leaves)})
            f.flush()
            os.fsync(f.fileno())
        if _WRITE_HOOK is not None:
            _WRITE_HOOK(step)
        # Fault-injection point (supervise/faults.py): SIGKILL here
        # models the classic preemption-mid-checkpoint crash the atomic
        # rename exists for.
        faults.fire("ckpt_mid_write", step=step)
        with _SWAP_LOCK:
            if os.path.isdir(final):
                # Re-save of the same step: move the old dir ASIDE and
                # delete it only after the new one is in place — a
                # writer killed between a plain rmtree and the replace
                # (e.g. an abandoned async thread dying at interpreter
                # exit while the preemption path re-saved the step)
                # must never leave the step missing entirely.
                trash = tmp + ".old"
                os.rename(final, trash)
            os.replace(tmp, final)
            # fsync the parent so the rename itself survives a power cut
            dfd = os.open(ckpt_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        if trash is not None:
            shutil.rmtree(trash, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        if trash is not None and not os.path.isdir(final):
            # the replace never landed: put the old step back
            try:
                os.rename(trash, final)
            except OSError:
                pass
        raise
    # Fault-injection point: the 'torn' action truncates the just-landed
    # npz, modeling a filesystem that lied about durability — the next
    # restore must walk back to the previous step.
    faults.fire("ckpt_after_write", step=step,
                path=os.path.join(final, STATE_FILE))
    _apply_retention(ckpt_dir, keep=max_to_keep)


def _apply_retention(ckpt_dir: str, keep: int) -> None:
    if keep <= 0:
        return
    steps = _all_steps(ckpt_dir)
    for s in steps[:-keep]:
        shutil.rmtree(os.path.join(ckpt_dir, str(s)), ignore_errors=True)


def _host_fetch(leaves) -> List[np.ndarray]:
    """Settle the (already started) D2H copies into numpy arrays."""
    return [np.asarray(jax.device_get(l)) for l in leaves]


def warm_async(state: TrainState) -> None:
    """Pre-compile the device-side snapshot program — the only compile on
    the async save path — so the FIRST in-loop save is O(dispatch) like
    every later one (the loop calls this during setup, where the cost
    lands outside any tick window; the persistent compile cache makes it
    a disk hit on warm runs)."""
    leaves, _ = jax.tree_util.tree_flatten(state)
    jax.block_until_ready(_device_snapshot(leaves))


def save(ckpt_dir: str, state: TrainState,
         cfg: Optional[ExperimentConfig] = None,
         max_to_keep: int = 5, block: bool = True) -> None:
    """Write one checkpoint step.

    ``block=False`` → async writeback: the call costs O(dispatch) on the
    calling thread (device-side copy + D2H start + thread handoff); the
    serialize/fsync/rename runs on the single-slot writer.  Call
    ``check_error`` at tick boundaries and ``wait`` before reading
    ``latest_step`` for dedupe/shutdown.  ``block=True`` serializes and
    writes inline (the ``--async-checkpoint off`` fallback and the final
    save).  Multi-host: the state is replicated, so only process 0
    writes; the call is a no-op elsewhere (no barrier required — the
    write involves no collectives).
    """
    if jax.process_index() != 0:
        return
    step = int(jax.device_get(state.step))
    with span("ckpt/save") as sp:
        leaves, _ = jax.tree_util.tree_flatten(state)
        if block:
            _write_state_dir(ckpt_dir, step, _host_fetch(leaves),
                             max_to_keep)
        else:
            # Device-side copy: the live state's buffers are donated to
            # the very next step dispatch, so the writer must own
            # independent buffers.  One fused async dispatch.
            snap = _device_snapshot(leaves)
            for l in snap:
                if hasattr(l, "copy_to_host_async"):
                    l.copy_to_host_async()
            _writer(ckpt_dir).submit(
                lambda: _write_state_dir(ckpt_dir, step, _host_fetch(snap),
                                         max_to_keep),
                label=f"step {step}")
    telemetry.gauge("ckpt/write_ms").set(sp.duration_s * 1000.0)
    telemetry.counter("ckpt/save_total").inc()
    if cfg is not None:
        cfg_path = os.path.join(ckpt_dir, "config.json")
        if not os.path.exists(cfg_path):
            os.makedirs(ckpt_dir, exist_ok=True)
            with open(cfg_path, "w") as f:
                f.write(cfg.to_json())


def reset_errors(ckpt_dir: str) -> None:
    """Run-start hygiene: drop any undelivered sticky error left on this
    directory's (process-cached) writer by a previous train() run that
    aborted between the failure and its tick-boundary poll — otherwise a
    healthy resume would crash on the PREVIOUS run's diagnostics."""
    key = os.path.abspath(ckpt_dir)
    if key in _WRITERS:
        _WRITERS[key].wait(reraise=False)
        _WRITERS[key].clear_error()


def check_error(ckpt_dir: str) -> None:
    """Re-raise a failed async write (the loop calls this every tick)."""
    key = os.path.abspath(ckpt_dir)
    if key in _WRITERS:
        _WRITERS[key].poll()


def wait(ckpt_dir: str, reraise: bool = True,
         timeout: Optional[float] = None) -> bool:
    """Join any in-flight async save for this directory.  ``reraise=False``
    is for ``finally`` blocks (a writer failure must not mask the
    exception already unwinding — it resurfaces via ``check_error`` /
    the next ``wait``).  ``timeout`` bounds the join (the preemption
    grace window: a wedged writer thread must not eat the final
    checkpoint's budget); returns False when the writer is still busy
    after it."""
    key = os.path.abspath(ckpt_dir)
    if key in _WRITERS:
        return _WRITERS[key].wait(reraise=reraise, timeout=timeout)
    return True


def _all_steps(ckpt_dir: str) -> List[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(int(d) for d in os.listdir(ckpt_dir)
                  if d.isdigit()
                  and os.path.isdir(os.path.join(ckpt_dir, d)))


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = _all_steps(ckpt_dir)
    return steps[-1] if steps else None


def _decode_leaf(arr: np.ndarray, t, path: str, k: str) -> jax.Array:
    """One npz leaf validated against its template leaf and copied into
    an XLA-owned buffer."""
    t_shape = tuple(getattr(t, "shape", ()))
    t_dtype = np.dtype(getattr(t, "dtype", arr.dtype))
    if arr.dtype.kind == "V" and arr.dtype.itemsize == t_dtype.itemsize:
        # extension dtypes (ml_dtypes bfloat16) round-trip through npz
        # as raw void bytes — reinterpret them against the template's
        # dtype (bit-exact)
        arr = arr.view(t_dtype)
    if tuple(arr.shape) != t_shape or arr.dtype != t_dtype:
        raise ValueError(
            f"checkpoint {path} leaf {k}: {arr.dtype}{arr.shape} "
            f"does not match template {t_dtype}{t_shape}")
    # jnp.array COPIES into an XLA-owned buffer.  Returning the raw
    # numpy leaf invites heap corruption downstream: on the CPU backend
    # device_put can zero-copy ALIAS a suitably aligned numpy buffer,
    # and the train steps donate the state — XLA would then reuse/free
    # memory owned by the Python allocator (observed as "corrupted
    # double-linked list" on the first post-resume step).
    return jnp.array(arr)


def _restore_npz(path: str, template: TrainState) -> TrainState:
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(path, allow_pickle=False) as z:
        keys = sorted(k for k in z.files if k.startswith("leaf_"))
        if len(keys) != len(t_leaves):
            raise ValueError(
                f"checkpoint {path} has {len(keys)} leaves, template has "
                f"{len(t_leaves)} — config/model mismatch?")
        out = [_decode_leaf(z[k], t, path, k)
               for k, t in zip(keys, t_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def restore_selected(ckpt_dir: str, template, select,
                     step: Optional[int] = None):
    """Partial restore: load ONLY the leaves whose pytree path satisfies
    ``select(path) -> bool``; every other position restores as ``None``.

    The serving path's checkpoint surface (ISSUE 10): a generation
    service needs ``ema_params`` + ``w_avg`` and nothing else, and the
    full-restore path forces the caller to materialize a CONCRETE
    template — i.e. run the whole G+D+optimizer init just to throw most
    of it away.  Here ``template`` may be an ABSTRACT TrainState
    (``jax.eval_shape`` over ``create_train_state`` — no device work at
    all); only the selected leaves are read from the npz, decoded, and
    copied onto the device.  npz-format checkpoints only — legacy Orbax
    step dirs (no ``state.npz``) raise ``FileNotFoundError`` so callers
    can fall back to the full ``restore``.
    """
    step = step if step is not None else latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(ckpt_dir, str(step), STATE_FILE)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} missing — pre-npz (Orbax) checkpoint; use the full "
            f"restore() with a concrete template")
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        template)
    with span("ckpt/restore_selected") as sp:
        with np.load(path, allow_pickle=False) as z:
            keys = sorted(k for k in z.files if k.startswith("leaf_"))
            if len(keys) != len(leaves_with_paths):
                raise ValueError(
                    f"checkpoint {path} has {len(keys)} leaves, template "
                    f"has {len(leaves_with_paths)} — config/model "
                    f"mismatch?")
            out = [(_decode_leaf(z[k], t, path, k) if select(p) else None)
                   for k, (p, t) in zip(keys, leaves_with_paths)]
    telemetry.gauge("ckpt/restore_selected_ms").set(sp.duration_s * 1000.0)
    return jax.tree_util.tree_unflatten(treedef, out)


def _restore_orbax(ckpt_dir: str, step: int,
                   template: TrainState) -> TrainState:
    """Legacy fallback for step dirs written by the pre-npz Orbax path."""
    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(os.path.abspath(ckpt_dir))
    return mgr.restore(step, args=ocp.args.StandardRestore(template))


def _quarantine(ckpt_dir: str, step: int) -> str:
    """Rename a step dir that failed to decode to ``<step>.corrupt`` so
    ``latest_step``/retention stop seeing it but a human still can (the
    bytes may be forensically interesting; they are NOT re-deleted by
    retention).  Returns the new path."""
    src = os.path.join(ckpt_dir, str(step))
    dst = os.path.join(ckpt_dir, f"{step}.corrupt")
    i = 0
    while os.path.exists(dst):           # repeated corruption of a re-save
        i += 1
        dst = os.path.join(ckpt_dir, f"{step}.corrupt{i}")
    os.replace(src, dst)
    return dst


def restore(ckpt_dir: str, template: TrainState,
            step: Optional[int] = None) -> TrainState:
    """Restore into the structure of ``template`` (shapes/dtypes come from
    the template; leaves come back as default-device jax arrays — callers
    ``device_put`` onto their mesh, which works under any layout).

    Latest-step restores (``step=None``) are RESILIENT: a torn or
    template-mismatched ``state.npz`` — the normal aftermath of a
    SIGKILL that beat the atomic rename's durability, or a filesystem
    that lied about it — walks back to the newest step that decodes
    cleanly.  The bad step dir is quarantined (renamed to
    ``<step>.corrupt``) so the next ``latest_step`` probe and retention
    skip it, and ``ckpt/restore_fallback_total`` counts the event.
    An EXPLICIT ``step`` keeps the old hard-fail contract — the caller
    asked for that step, substituting another would be a silent lie.
    Legacy Orbax step dirs (no npz) never quarantine: their errors are
    environmental (package missing), not evidence of corruption."""
    explicit = step is not None
    candidates = [step] if explicit else list(reversed(_all_steps(ckpt_dir)))
    if not candidates:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    last_err: Optional[Exception] = None
    with span("ckpt/restore") as sp:
        for s in candidates:
            step_dir = os.path.join(ckpt_dir, str(s))
            npz = os.path.join(step_dir, STATE_FILE)
            if not os.path.exists(npz):
                if not explicit and not os.path.isdir(step_dir):
                    # a peer process quarantined this step between our
                    # directory listing and here (shared run dir,
                    # multi-host resume) — walk on
                    continue
                out = _restore_orbax(ckpt_dir, s, template)
                break
            try:
                out = _restore_npz(npz, template)
                break
            except Exception as e:
                if explicit:
                    raise
                try:
                    quarantined = _quarantine(ckpt_dir, s)
                except (FileNotFoundError, OSError):
                    # a peer's quarantine rename won the race — same
                    # verdict, no need to move anything ourselves
                    quarantined = f"{s}.corrupt (by a peer process)"
                telemetry.counter("ckpt/restore_fallback_total").inc()
                print(f"[ckpt] step {s} failed to decode "
                      f"({type(e).__name__}: {str(e)[:200]}); quarantined "
                      f"to {quarantined}, walking back", flush=True)
                last_err = e
        else:
            err = ValueError(
                f"no checkpoint under {ckpt_dir} decodes cleanly"
                + (f"; last error: {type(last_err).__name__}: {last_err}"
                   if last_err is not None else
                   " (every candidate vanished mid-walk — quarantined "
                   "or pruned by a peer process?)"))
            if last_err is not None:
                raise err from last_err
            raise err
    telemetry.gauge("ckpt/restore_ms").set(sp.duration_s * 1000.0)
    return out
