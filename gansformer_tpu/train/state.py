"""Train state — everything the training loop owns, as one pytree.

The reference scatters this across TF1 graph variables: G/D vars inside
``tflib.Network`` objects, Adam slots inside ``tflib.Optimizer``, the EMA
clone ``Gs``, ``w_avg``/``pl_mean`` as graph vars, and kimg accounting in
Python (SURVEY.md §2.2, §3.1).  Here it is a single ``flax.struct`` pytree:
jit-donatable, orbax-checkpointable as a unit (deliberately *better* than the
reference, which silently drops Adam moments on resume — SURVEY.md §7.4).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax

from gansformer_tpu.core.config import ExperimentConfig
from gansformer_tpu.models.discriminator import Discriminator
from gansformer_tpu.models.generator import Generator


@flax.struct.dataclass
class TrainState:
    step: jax.Array                  # int32 scalar; cur_nimg = step * batch
    g_params: Any
    d_params: Any
    g_opt: Any                       # optax state (two-timescale: separate)
    d_opt: Any
    ema_params: Any                  # Gs — EMA generator used for all eval
    w_avg: jax.Array                 # [w_dim] mapping-output EMA (truncation)
    pl_mean: jax.Array               # scalar path-length EMA

    @property
    def cur_nimg(self):
        return self.step


def lazy_adam(lr: float, beta1: float, beta2: float, eps: float,
              reg_interval: int) -> optax.GradientTransformation:
    """Adam with lazy-regularization coefficient correction.

    When a regularizer only fires every ``I`` steps the reference rescales
    lr and betas by ``c = I/(I+1)`` (StyleGAN2's lazy-reg trick) so the
    effective optimization trajectory matches a per-step regularizer.
    """
    c = reg_interval / (reg_interval + 1.0)
    return optax.adam(lr * c, b1=beta1**c, b2=beta2**c, eps=eps)


def make_optimizers(cfg: ExperimentConfig):
    t = cfg.train
    g_tx = lazy_adam(t.g_lr, t.adam_beta1, t.adam_beta2, t.adam_eps,
                     t.g_reg_interval)
    d_tx = lazy_adam(t.d_lr, t.adam_beta1, t.adam_beta2, t.adam_eps,
                     t.d_reg_interval)
    return g_tx, d_tx


def create_train_state(cfg: ExperimentConfig, rng: jax.Array) -> TrainState:
    m = cfg.model
    G = Generator(m)
    D = Discriminator(m)
    k_g, k_d, k_noise = jax.random.split(rng, 3)
    z = jnp.zeros((2, m.num_ws, m.latent_dim), jnp.float32)
    img = jnp.zeros((2, m.resolution, m.resolution, m.img_channels), jnp.float32)
    label = jnp.zeros((2, m.label_dim), jnp.float32) if m.label_dim else None
    g_vars = G.init({"params": k_g, "noise": k_noise}, z, label=label)
    d_vars = D.init({"params": k_d}, img, label)
    g_params, d_params = g_vars["params"], d_vars["params"]
    g_tx, d_tx = make_optimizers(cfg)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        g_params=g_params,
        d_params=d_params,
        g_opt=g_tx.init(g_params),
        d_opt=d_tx.init(d_params),
        ema_params=jax.tree_util.tree_map(jnp.copy, g_params),
        w_avg=jnp.zeros((m.w_dim,), jnp.float32),
        pl_mean=jnp.zeros((), jnp.float32),
    )


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
