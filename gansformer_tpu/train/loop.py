"""Training loop — engine parity with ``src/training/training_loop.py``
(SURVEY.md §3.1), re-shaped for the JAX async-dispatch model.

Per iteration: one D step and one G step (alternating, two separate Adam
optimizers — "two-timescale", BASELINE.json:5), with the lazy-reg variants
(R1 every ``d_reg_interval``, path-length every ``g_reg_interval``) selected
*in Python* from the static step index so each variant is its own jit
specialization (SURVEY.md §7.3 item 2).

Throughput discipline (the ≥200 img/sec/chip target dies on host syncs —
§7.3 item 4): device metrics are only fetched at tick boundaries; the step
functions donate the state pytree, so the loop body enqueues work and
immediately continues.
"""

from __future__ import annotations

import math
import os
import signal
import threading
import time
from typing import Optional

import jax
import numpy as np

from gansformer_tpu import obs
from gansformer_tpu.supervise import faults
from gansformer_tpu.supervise.events import PreemptionExit
from gansformer_tpu.core.config import ExperimentConfig
from gansformer_tpu.data.dataset import PrefetchIterator, make_dataset
from gansformer_tpu.data.device_prefetch import DevicePrefetcher
from gansformer_tpu.obs.spans import span
from gansformer_tpu.utils.background import SingleSlotWriter
from gansformer_tpu.parallel.mesh import MeshEnv, local_batch_size, make_mesh
from gansformer_tpu.train import checkpoint as ckpt
from gansformer_tpu.train.state import TrainState, create_train_state, param_count
from gansformer_tpu.train.steps import make_metric_samplers, make_train_steps
from gansformer_tpu.utils.image import save_image_grid
from gansformer_tpu.utils.logging import RunLogger


def estimate_iteration_flops(cfg: ExperimentConfig, fns, state,
                             batch_sharding) -> Optional[float]:
    """Cadence-weighted per-iteration FLOPs (per device), or None.

    Lowers the four phase programs with abstract args matching the real
    dispatch and reads XLA cost analysis — the same derivation bench.py's
    ``measure_cycle`` uses for fused-cycle FLOPs (cycle cost = Σ phase
    FLOPs × cadence; the cycle program's own cost analysis counts its scan
    bodies once, not × trip count, so it cannot be read directly).  Under
    ``--fused-cycle`` these four programs are never dispatched, but
    ``lower().compile()`` shares the persistent compile cache with bench.py
    and the unfused loop, so a warm run pays four cache round-trips, not
    four compiles.  Platform-agnostic by design: the TPU gate lives at the
    call site, so a CPU test can exercise this path directly.
    """
    from gansformer_tpu.utils.benchcheck import cadence_weighted, flops_of

    t = cfg.train
    imgs_s = jax.ShapeDtypeStruct(
        (t.batch_size, cfg.model.resolution, cfg.model.resolution,
         cfg.model.img_channels), np.uint8, sharding=batch_sharding)
    lbl_s = (jax.ShapeDtypeStruct(
        (t.batch_size, cfg.model.label_dim), np.float32,
        sharding=batch_sharding)
        if cfg.model.label_dim else None)
    key_s = jax.ShapeDtypeStruct((2,), np.uint32)
    ph = {}
    for name, fn, extra in (
            ("d", fns.d_step, (imgs_s, key_s, lbl_s)),
            ("g", fns.g_step, (key_s, lbl_s)),
            ("d_r1", fns.d_step_r1, (imgs_s, key_s, lbl_s)),
            ("g_pl", fns.g_step_pl, (key_s, lbl_s))):
        fl = flops_of(fn.lower(state, *extra).compile())
        if fl:
            ph[name] = fl
    if not all(k in ph for k in ("d", "g", "d_r1", "g_pl")):
        return None
    return cadence_weighted(ph, t.d_reg_interval, t.g_reg_interval)


def wattn_gate_stats(g_params) -> Optional[dict]:
    """ReZero attention-gate observability (VERDICT r5 weak #5).

    max/mean |gate| over every ``b*_wattn_gate`` scalar in the generator
    tree — the gates are the mechanism by which attention-driven styling
    comes online (models/synthesis.py), so a run where they stay pinned
    at 0 (attention styling dead) must be distinguishable from a healthy
    run in stats.jsonl.  Returns None when the config has no such gates
    (style_mode='global' or attention='none').  Fetches a handful of
    scalars — call it at the tick boundary, the loop's one sync point.
    """
    vals = [v for path, v in jax.tree_util.tree_leaves_with_path(g_params)
            if any("wattn_gate" in str(getattr(k, "key", k)) for k in path)]
    if not vals:
        return None
    mags = np.abs(np.asarray(jax.device_get(vals), np.float32))
    return {"gates/wattn_max": float(mags.max()),
            "gates/wattn_mean": float(mags.mean())}


def resolve_conditional(cfg: ExperimentConfig, dataset) -> ExperimentConfig:
    """A labeled dataset flips G/D into conditional mode (VERDICT r2 item 7:
    the label path is consumed end-to-end, not half-connected)."""
    if dataset.has_labels and cfg.model.label_dim == 0:
        import dataclasses

        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(
                cfg.model, label_dim=dataset.label_dim))
    return cfg


class _PreemptNotice:
    """SIGTERM → graceful-checkpoint request (ROADMAP item 5).

    The handler only flips a flag; the loop polls it at dispatch
    boundaries (signal-handler-safe by construction — no locks, no I/O).
    ``shutdown_timeout_s`` is set once preemption shutdown begins so the
    ``finally`` path bounds its writer joins to the remaining grace
    window instead of blocking on a possibly-wedged thread."""

    def __init__(self):
        self.requested = False
        self.shutdown_timeout_s: Optional[float] = None

    def _handler(self, signum, frame):
        self.requested = True

    def install(self):
        """Install on SIGTERM when possible (main thread only — tests
        and library callers off the main thread just never see the
        graceful path).  Returns a restore callable."""
        if threading.current_thread() is not threading.main_thread():
            return lambda: None
        try:
            prev = signal.signal(signal.SIGTERM, self._handler)
        except (ValueError, OSError):
            return lambda: None
        return lambda: signal.signal(signal.SIGTERM, prev)


def preempt_grace_s() -> float:
    """The SIGTERM→exit budget (seconds).  The supervisor exports it to
    the child's env; standalone runs get a conservative default."""
    try:
        return float(os.environ.get("GANSFORMER_TPU_PREEMPT_GRACE_S", "30"))
    except ValueError:
        return 30.0


def _preemption_checkpoint(state, ckpt_dir: str, cfg: ExperimentConfig,
                           grace: float) -> int:
    """The graceful-preemption endgame (runs ONCE, not per iteration —
    deliberately outside the hot loop and its sync discipline): settle
    the in-flight step, bound the async-writer join to the grace window
    (a wedged daemon writer must not eat it), and write one final
    synchronous checkpoint unless the current step is already on disk.
    Returns the step the run exits at."""
    jax.block_until_ready(state.step)
    ckpt.wait(ckpt_dir, reraise=False, timeout=max(1.0, grace / 2))
    step_now = int(jax.device_get(state.step))
    if ckpt.latest_step(ckpt_dir) != step_now:
        with span("checkpoint"):
            ckpt.save(ckpt_dir, state, cfg, block=True)
    return step_now


def train(cfg: ExperimentConfig, run_dir: str,
          env: Optional[MeshEnv] = None,
          resume: bool = False,
          total_kimg: Optional[int] = None,
          logger: Optional[RunLogger] = None) -> TrainState:
    cfg.validate()
    env = env or make_mesh(cfg.mesh)
    # Ambient mesh for the whole run: sequence-parallel grid constraints
    # (ModelConfig.sequence_parallel) resolve bare PartitionSpecs against it.
    # RunLogger as context manager: stats.jsonl/log.txt/TensorBoard files
    # close (and the last write is flushed) even when training raises.
    # SIGTERM = preemption notice: installed for the whole run (compiles
    # included) so a notice during setup still resolves at the first
    # loop-boundary poll instead of killing the process mid-compile.
    preempt = _PreemptNotice()
    restore_handler = preempt.install()
    try:
        with env.activate():
            with (logger or RunLogger(run_dir)) as log:
                return _train(cfg, run_dir, env, resume, total_kimg, log,
                              preempt)
    finally:
        restore_handler()


def _train(cfg: ExperimentConfig, run_dir: str,
           env: MeshEnv,
           resume: bool,
           total_kimg: Optional[int],
           log: RunLogger,
           preempt: Optional[_PreemptNotice] = None) -> TrainState:
    preempt = preempt or _PreemptNotice()
    t = cfg.train
    total_kimg = total_kimg if total_kimg is not None else t.total_kimg

    # --- telemetry (gansformer_tpu/obs) --------------------------------------
    # Tracer: per-phase wall-time spans → events.jsonl (process 0 owns the
    # run dir's trace file, same ownership rule as RunLogger) + per-tick
    # timing/phase/* stats.  Reset first: a previous train() in this
    # process (tests run several) must not leak span totals into tick 0.
    tracer = obs.get_tracer()
    tracer.reset()
    # Registry likewise: telemetry.prom / the stats.jsonl telemetry section
    # are PER-RUN artifacts, so a second train() in this process (the
    # experiment CLI's arms, back-to-back tests) must start from zero.
    # Safe: every instrumentation site created after this point (prefetch
    # iterator) or resolving per call (ckpt, metrics, compile listener).
    obs.get_registry().reset()
    tracer.configure(
        os.path.join(run_dir, "events.jsonl")
        if jax.process_index() == 0 else None,
        process_index=jax.process_index(),
        truncate=not resume)
    obs.install_compile_listener()  # compile/compiles_total + compile_ms
    # Post-warm-up compiles are retraces (compile/retraces_total) — the
    # runtime cross-check of the static retrace-hazard trace rule: armed
    # at the first tick boundary (all step variants compiled by then),
    # polled every tick.  docs/observability.md "Compilation".
    retrace_watch = obs.RetraceWatch()
    # Heartbeat: EVERY process writes its own liveness file so a stalled
    # peer is visible from outside while the survivors sit in a collective.
    # The first beat waits until state/restore resolves cur_nimg — beating
    # step=0 here would overwrite a crashed run's last-progress record
    # with zeros the moment --resume starts.
    heartbeat = obs.Heartbeat(run_dir, jax.process_index())
    prom_path = os.path.join(run_dir, "telemetry.prom")
    if t.debug_nans:
        from gansformer_tpu.utils.debug import enable_nan_debug

        enable_nan_debug()
        log.write("debug: jax_debug_nans ON (op-by-op NaN localization)")

    # Data-plane robustness family (ISSUE 15): materialized up front so
    # absence in telemetry.prom always means "wiring rotted", never
    # "nothing went wrong" (the schema lint's explicit-marker
    # discipline); the corrupt-frac budget gauge records the threshold
    # the doctor judges the ratio against.
    for c in ("data/read_retries_total", "data/corrupt_records_total",
              "data/stalls_total"):
        obs.get_registry().counter(c)
    # Conv-family fallback counters (ISSUE 17): same discipline — a 0 in
    # the scrape is a positive "no silent XLA fallback" claim.  The
    # dispatchers (ops/pallas_modconv.py, ops/upfirdn2d.py) increment
    # these at trace time via ops.pallas_upfirdn.note_conv_fallback.
    for c in ("ops/modconv_fallback_total", "ops/modconv_fallback_shape_total",
              "ops/modconv_fallback_vmem_total"):
        obs.get_registry().counter(c)
    # Nonfinite cross-check (ISSUE 19): the runtime twin of graftnum's
    # static fp32-island audit.  Classified at the tick boundary from
    # values the tick already fetched — no extra device sync — and
    # materialized here so a 0 in the scrape is a positive "no NaN/inf
    # reached the host" claim (telemetry_schema requires the family;
    # the doctor WARNs on any nonzero cause).
    for c in ("train/nonfinite_total", "train/nonfinite_loss_total",
              "train/nonfinite_grad_total", "train/nonfinite_param_total"):
        obs.get_registry().counter(c)
    obs.get_registry().gauge("data/corrupt_frac").set(0.0)
    obs.get_registry().gauge("data/corrupt_budget_frac").set(
        cfg.data.max_corrupt_frac)

    # The dataset decides the conditional path: a labeled dataset switches
    # G/D into conditional mode unless the config already pinned label_dim.
    dataset = make_dataset(cfg.data)
    # Corruption quarantine ledger (offset+cause per quarantined record):
    # entries noted at index-build time flush here too.
    dataset.set_quarantine_ledger(
        os.path.join(run_dir, "data_quarantine.jsonl"))
    cfg = resolve_conditional(cfg, dataset)
    if jax.process_index() == 0:
        # Re-record the *resolved* config so generate/evaluate rebuild the
        # exact model that was trained (label_dim changes the param tree).
        with open(os.path.join(run_dir, "config.json"), "w") as f:
            f.write(cfg.to_json())

    n_chips = env.mesh.size
    # validate() covers explicit mesh.data; with the default data=-1 the
    # axis size is the device count, known only once the mesh is built —
    # check here so a pod run fails with words, not a sharding traceback.
    if t.batch_size % env.data_size:
        raise ValueError(
            f"train.batch_size ({t.batch_size}) is not divisible by the "
            f"resolved data-axis size ({env.data_size}); pick a batch that "
            f"splits evenly across the data mesh axis")
    log.write(f"mesh: {dict(zip(env.mesh.axis_names, env.mesh.devices.shape))} "
              f"({n_chips} devices, {jax.process_count()} processes)")
    log.write(f"config: {cfg.name}  resolution {cfg.model.resolution}  "
              f"attention {cfg.model.attention}  k={cfg.model.components}"
              + (f"  label_dim={cfg.model.label_dim}"
                 if cfg.model.label_dim else ""))

    # --- state ---------------------------------------------------------------
    rng = jax.random.PRNGKey(t.seed)
    state = create_train_state(cfg, rng)
    log.write(f"G params: {param_count(state.g_params):,}  "
              f"D params: {param_count(state.d_params):,}")
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    # A previous train() in this process (retry, tests) may have left an
    # undelivered async-writer error on this directory — it was THAT
    # run's diagnostics, not this one's.
    ckpt.reset_errors(ckpt_dir)
    resumed = False
    if resume and ckpt.latest_step(ckpt_dir) is not None:
        # restore() walks back past torn/corrupt latest steps
        # (quarantining them), so the step actually restored is read
        # from the state, not from the directory listing.
        state = ckpt.restore(ckpt_dir, state)
        resumed = True
    # ONE step fetch feeds the resume log, the data-stream alignment
    # (start_batch), and the loop's starting counters below — deriving
    # them separately invites a silent divergence that would break the
    # tick-for-tick resume-parity contract.
    start_step = int(jax.device_get(state.step))
    if resumed:
        log.write(f"resumed from step {start_step} "
                  f"({start_step / 1000:.1f} kimg)")
        if jax.process_index() == 0:
            # One line per restart (resumes.jsonl + the supervisor
            # ledger): the run doctor's restart-count / availability
            # evidence (ROADMAP item 5).
            from gansformer_tpu.utils.logging import append_resume_record

            append_resume_record(run_dir, step=start_step)

    # State placement: params/EMA/stats replicated across the mesh;
    # under --fsdp the optimizer moments shard per-leaf over the data
    # axis (parallel/contracts.state_shardings — the SAME derivation
    # the partition-contract rule asserts).  Batches arrive sharded on
    # 'data' either way.
    if cfg.mesh.fsdp:
        from gansformer_tpu.parallel.contracts import state_shardings

        placements = state_shardings(state, env, fsdp=True)
        state = jax.device_put(state, placements)
        n_shard = sum(1 for s in jax.tree_util.tree_leaves(placements)
                      if not s.is_fully_replicated)
        log.write(f"fsdp: optimizer state sharded over data={env.data_size} "
                  f"({n_shard} sharded leaves; params/EMA replicated)")
    else:
        state = jax.device_put(state, env.replicated())
    fns = make_train_steps(cfg, env, batch_size=t.batch_size)
    if t.async_checkpoint and t.snapshot_ticks:
        # Compile the async-save staging program NOW (setup, outside any
        # tick window) so the first in-loop checkpoint is O(dispatch).
        ckpt.warm_async(state)

    # --- data ----------------------------------------------------------------
    shard = (jax.process_index(), jax.process_count())
    # Each process produces only its share of the global batch; the global
    # array is assembled from process-local shards (no cross-host shuffle —
    # SURVEY.md §7.3 item 6).
    multihost = jax.process_count() > 1
    local_bs = local_batch_size(t.batch_size, env) if multihost else t.batch_size
    # start_batch aligns the data stream to the restored step: a resumed
    # run re-consumes the SAME batch sequence an uninterrupted run would
    # see at that iteration, which is what makes kill→resume loss
    # trajectories tick-for-tick identical (tests/test_supervise.py).
    start_it = start_step // t.batch_size
    batch_iter = dataset.batches(local_bs, seed=t.seed + 1, shard=shard,
                                 start_batch=start_it)
    batch_sharding = env.batch()

    def put_batch(host_arr: np.ndarray) -> jax.Array:
        if multihost:
            return jax.make_array_from_process_local_data(
                batch_sharding, host_arr)
        return jax.device_put(host_arr, batch_sharding)

    # Fused lazy-reg cycle (TrainConfig.fused_cycle): one dispatch per
    # d_reg_interval iterations; inputs are K stacked batches sharded on
    # axis 1 (the batch axis).
    use_cycle = t.fused_cycle and fns.cycle is not None
    stack_sharding = env.batch_stack()

    def put_stack(host_arr: np.ndarray) -> jax.Array:
        if multihost:
            return jax.make_array_from_process_local_data(
                stack_sharding, host_arr)
        return jax.device_put(host_arr, stack_sharding)

    if use_cycle:
        log.write(f"fused cycle: {fns.cycle_len} iterations per dispatch")

    # --- implied-MFU bookkeeping (TPU only) ----------------------------------
    # Cadence-weighted per-iteration FLOPs (XLA cost analysis, per-device
    # under SPMD) + the chip's bf16 peak turn every tick's img/s into a
    # ``timing/mfu`` the reader can check against physics — the same
    # self-validation bench.py applies to its own numbers (PERF.md §1b).
    # lower().compile() shares the persistent compile cache with the loop's
    # own jit calls, so this costs one cache round-trip per phase, not a
    # second compile.
    # Runs in BOTH dispatch modes — especially --fused-cycle, the mode the
    # flagship TPU run uses (VERDICT r4 weak #3): the four phase lowerings
    # feed cost analysis even when only fns.cycle is dispatched.
    # GANSFORMER_TPU_FORCE_MFU=<peak TFLOP/s> is the CPU test hook: it
    # both enables the path off-TPU and supplies the synthetic peak that
    # peak_tflops() has no table entry for.
    flops_per_it = peak = None
    force_peak = os.environ.get("GANSFORMER_TPU_FORCE_MFU")
    if jax.devices()[0].platform == "tpu" or force_peak:
        try:
            from gansformer_tpu.utils.benchcheck import peak_tflops

            peak = (float(force_peak) if force_peak
                    else peak_tflops(jax.devices()[0].device_kind))
            if peak:
                # Sharded abstract args matching the REAL dispatch — both
                # so the persistent-cache entry is the one the unfused
                # loop's first call hits, and so cost analysis runs on the
                # same partitioned per-device module.
                flops_per_it = estimate_iteration_flops(
                    cfg, fns, state, batch_sharding)
                if flops_per_it:
                    log.write(
                        f"mfu bookkeeping: {flops_per_it / 1e12:.3f} "
                        f"TFLOP/iteration (cadence-weighted, per device), "
                        f"peak {peak} TFLOP/s")
        except Exception as e:   # never let bookkeeping kill training
            log.write(f"mfu bookkeeping unavailable: "
                      f"{type(e).__name__}: {str(e)[:200]}")
            flops_per_it = None

    # --- device-truth sampler (ISSUE 8) --------------------------------------
    # Periodic jax.profiler windows — one full tick traced every
    # device_time_ticks ticks, parsed (utils/profparse.py: xplane or the
    # Chrome-trace fallback) and folded into device/* gauges: per-program
    # device ms, device-time MFU beside the wall-clock timing/mfu, and
    # the wall-vs-device divergence ratio that would have caught the
    # retracted r3 number.  Process 0 only (it owns telemetry.prom); the
    # one-shot profile_dir trace owns the profiler when set.
    sampler = obs.DeviceTimeSampler(
        every_ticks=t.device_time_ticks,
        flops_per_it=flops_per_it, peak_tflops=peak,
        enabled=jax.process_index() == 0 and not t.profile_dir)

    # --- fixed grid latents for snapshots ------------------------------------
    grid_n = min(16, t.batch_size * 2)
    grid_z = jax.random.normal(
        jax.random.PRNGKey(t.seed + 2),
        (grid_n, cfg.model.num_ws, cfg.model.latent_dim), np.float32)
    grid_labels = (dataset.random_labels(grid_n, seed=t.seed + 2)
                   if cfg.model.label_dim else None)
    noise_key = jax.random.PRNGKey(t.seed + 3)

    # Async writeback (TrainConfig.async_checkpoint): image grids are
    # sampled on the loop thread (dispatch only), the device→host copy is
    # started non-blocking, and the PNG encode + file write runs on a
    # bounded single-slot writer thread.  The sampled array is a fresh
    # (non-donated) output, so the writer can settle it at leisure.
    snap_writer = SingleSlotWriter("snapshot/async") \
        if t.async_checkpoint else None

    def snapshot_images(st: TrainState, kimg: float) -> None:
        path = os.path.join(run_dir, f"fakes{int(kimg):06d}.png")
        with span("snapshot"):
            imgs = fns.sample(st.ema_params, st.w_avg, grid_z, noise_key,
                              truncation_psi=0.7, label=grid_labels)
            if snap_writer is not None:
                if hasattr(imgs, "copy_to_host_async"):
                    imgs.copy_to_host_async()
                snap_writer.submit(
                    lambda: save_image_grid(
                        np.asarray(jax.device_get(imgs)), path),
                    label=os.path.basename(path))
            else:
                save_image_grid(np.asarray(jax.device_get(imgs)), path)

    metric_group = None  # built lazily once; Inception init/jit is costly

    def run_metrics(st: TrainState):
        """Per-snapshot metric runs — reference training_loop parity
        (SURVEY.md §3.1 'periodic metric runs')."""
        nonlocal metric_group
        if metric_group is None:
            from gansformer_tpu.metrics.inception import make_extractor
            from gansformer_tpu.metrics.metric_base import (
                MetricGroup, parse_metric_names)

            metric_group = MetricGroup(
                parse_metric_names(t.metrics, batch_size=t.batch_size),
                extractor=make_extractor(env=env),  # sweep sharded over mesh
                cache_dir=os.path.join(run_dir, "metric-cache"))
        group = metric_group
        sample_fn, pair_fn = make_metric_samplers(
            fns, st, cfg, env, dataset, truncation_psi=1.0, seed=t.seed + 5)
        return group.run(sample_fn, dataset, pair_fn=pair_fn)

    # --- loop ----------------------------------------------------------------
    cur_nimg = start_step
    # phase="setup": this beat precedes the first-dispatch compiles, so
    # a supervisor must keep judging liveness against its STARTUP grace
    # (not the steady-state heartbeat budget) until a tick beat lands —
    # supervise/supervisor.probe_hang reads the phase for exactly that.
    heartbeat.beat(step=cur_nimg, kimg=cur_nimg / 1000,
                   extra={"phase": "setup"})
    it = start_it
    tick = 0
    tick_start_nimg = cur_nimg
    # Setup spans (ckpt/restore on resume) ran outside any tick window:
    # clear the phase accumulators so tick 0's timing/phase/* partitions
    # only its own wall time (the spans stay in events.jsonl regardless).
    tracer.drain()
    tick_start_time = time.time()
    # Tick-averaged scalars (the reference's autosummary semantics): per-key
    # running sums accumulate ON DEVICE (a handful of scalar adds per step,
    # no host sync); the tick boundary fetches sum/count.  Keys differ
    # between reg and plain step variants, so counts are per key.
    acc_sum: dict = {}
    acc_cnt: dict = {}
    snapshot_images(state, cur_nimg / 1000)

    # Host-side decode/shuffle runs in a background thread so the device
    # never waits on input (cfg.data.prefetch = queue depth in batches).
    # Constructed HERE, directly before the try, so the producer thread can
    # never leak if anything earlier raises.
    batches = PrefetchIterator(batch_iter, depth=cfg.data.prefetch,
                               stall_after_s=cfg.data.stall_after_s)

    # Device-resident input prefetch (DataConfig.device_prefetch): a second
    # background thread pulls host batches, device_puts them onto their
    # shardings, and keeps a small ring already in HBM — the loop's h2d
    # phase collapses to a queue pop.  The plan generator mirrors the loop
    # body's single-vs-fused-cycle branch arithmetic exactly, so the data
    # stream order (and therefore the rng/loss trajectory) is IDENTICAL to
    # the synchronous path — parity is held by tests/test_device_prefetch.
    dev_batches = None
    if cfg.data.device_prefetch:
        def host_plan(start_it):
            i = start_it
            while True:
                if use_cycle and i % t.d_reg_interval == 0:
                    bl = [next(batches) for _ in range(fns.cycle_len)]
                    item = {"image": np.stack([b["image"] for b in bl])}
                    if cfg.model.label_dim and "label" in bl[0]:
                        item["label"] = np.stack([b["label"] for b in bl])
                    yield ("stack", item)
                    i += fns.cycle_len
                else:
                    b = next(batches)
                    item = {"image": b["image"]}
                    if cfg.model.label_dim and "label" in b:
                        item["label"] = b["label"]
                    yield ("single", item)
                    i += 1

        def put_item(tagged):
            kind, d = tagged
            put = put_stack if kind == "stack" else put_batch
            return kind, {k: put(v) for k, v in d.items()}

        dev_batches = DevicePrefetcher(
            host_plan(it), put_item, depth=cfg.data.device_prefetch_depth,
            stall_after_s=cfg.data.stall_after_s)
    # jax.profiler trace (SURVEY.md §5 tracing row): the trace runs between
    # the first and second tick boundaries, i.e. it captures the SECOND tick
    # window — the one the stats log labels ``Progress/tick: 1``.  The first
    # window pays the compiles; the traced one is steady state, which is the
    # window worth seeing in TensorBoard's profile plugin.
    profiling = False
    base_rng = jax.random.PRNGKey(t.seed + 4)
    try:
        while cur_nimg < total_kimg * 1000:
            if preempt.requested:
                # Graceful preemption (SIGTERM): ONE final synchronous
                # checkpoint + flush inside the grace window, then a
                # distinct exit the supervisor classifies as preemption,
                # not crash.
                grace = preempt_grace_s()
                preempt.shutdown_timeout_s = max(1.0, grace / 4)
                log.write(f"preemption notice (SIGTERM): final "
                          f"checkpoint within {grace:.0f}s grace")
                step_now = _preemption_checkpoint(state, ckpt_dir, cfg,
                                                  grace)
                log.write(f"preemption checkpoint @ step {step_now}; "
                          f"exiting for resume")
                raise PreemptionExit(step_now)
            # Phase spans (obs/spans.py): data_wait is the time the loop
            # BLOCKS on the prefetch queue — previously folded silently
            # into step time; h2d is host→device transfer/assembly; step
            # is dispatch (under async dispatch the device work itself
            # settles inside tick_fetch's block_until_ready).
            if use_cycle and it % t.d_reg_interval == 0:
                # One dispatch = a full lazy-reg cycle.  Per-iteration rng
                # derivation inside matches the unfused path exactly
                # (held to parity in tests/test_train.py).
                k_cycle = fns.cycle_len
                if dev_batches is not None:
                    # Overlapped input: the ring pop is the only wait (an
                    # empty ring means the transfer thread is behind —
                    # genuine data starvation, so it belongs in
                    # data_wait/‑frac).  The loop thread does NO h2d work:
                    # the transfer ran on the background thread (its real
                    # cost is the data/h2d_ms histogram); the empty span
                    # keeps timing/phase/h2d present for dashboards.
                    with span("data_wait"):
                        kind, dev = dev_batches.get()
                        assert kind == "stack", kind
                        imgs_k = dev["image"]
                        label_k = dev.get("label")
                    with span("h2d"):
                        pass
                else:
                    with span("data_wait"):
                        batch_list = [next(batches) for _ in range(k_cycle)]
                    with span("h2d"):
                        imgs_k = put_stack(np.stack(
                            [b["image"] for b in batch_list]))
                        label_k = (put_stack(np.stack(
                            [b["label"] for b in batch_list]))
                            if cfg.model.label_dim and
                            "label" in batch_list[0]
                            else None)
                with span("step"):
                    # base_rng is the cycle's API: it folds in the global
                    # iteration index per contained step itself
                    state, sums = fns.cycle(state, imgs_k, base_rng, it,  # graftlint: disable=rng-key-reuse
                                            label_k)
                    it += k_cycle
                    cur_nimg += t.batch_size * k_cycle
                    for k, v in sums.items():
                        acc_sum[k] = v if k not in acc_sum else acc_sum[k] + v
                        acc_cnt[k] = acc_cnt.get(k, 0) + fns.cycle_counts[k]
            else:
                if dev_batches is not None:
                    # see the fused-cycle branch above for the span layout
                    with span("data_wait"):
                        kind, dev = dev_batches.get()
                        assert kind == "single", kind
                        imgs = dev["image"]
                        label = dev.get("label")
                    with span("h2d"):
                        pass
                else:
                    with span("data_wait"):
                        batch = next(batches)
                    with span("h2d"):
                        imgs = put_batch(batch["image"])
                        label = (put_batch(batch["label"])
                                 if cfg.model.label_dim and "label" in batch
                                 else None)
                with span("step"):
                    step_rng = jax.random.fold_in(base_rng, it)

                    d_fn = (fns.d_step_r1 if (it % t.d_reg_interval == 0)
                            else fns.d_step)
                    state, d_aux = d_fn(state, imgs,
                                        jax.random.fold_in(step_rng, 0),
                                        label)
                    g_fn = (fns.g_step_pl if (it % t.g_reg_interval == 0)
                            else fns.g_step)
                    state, g_aux = g_fn(state,
                                        jax.random.fold_in(step_rng, 1),
                                        label)

                    it += 1
                    cur_nimg += t.batch_size
                    for k, v in {**d_aux, **g_aux}.items():
                        acc_sum[k] = v if k not in acc_sum else acc_sum[k] + v
                        acc_cnt[k] = acc_cnt.get(k, 0) + 1

            # --- tick boundary (the ONLY host sync) -------------------------
            if cur_nimg >= tick_start_nimg + t.kimg_per_tick * 1000 or \
                    cur_nimg >= total_kimg * 1000:
                with span("tick_fetch"):
                    jax.block_until_ready(state.step)
                    now = time.time()
                    sec_per_tick = now - tick_start_time
                    imgs_done = cur_nimg - tick_start_nimg
                    if t.async_checkpoint:
                        # Start every D2H copy before settling any of
                        # them: the per-scalar fetches below then collapse
                        # from N serial round-trips to one settle pass
                        # (the device values were computed during the
                        # tick; only the transfers remain).
                        for v in acc_sum.values():
                            if hasattr(v, "copy_to_host_async"):
                                v.copy_to_host_async()
                    fetched = {k: float(jax.device_get(v)) / acc_cnt[k]
                               for k, v in acc_sum.items()}
                    # A handful of scalar gate params (None when the
                    # config has no attention-styling gates).
                    gate_stats = wattn_gate_stats(state.g_params)
                acc_sum, acc_cnt = {}, {}
                # graftnum runtime cross-check (ISSUE 19): the static
                # audit proves the islands compute in fp32; this counts
                # any non-finite value that still reaches the host,
                # labelled by cause — the lazy-reg penalty metrics
                # ("/r1", "/pl") ride the gradient path, other fetched
                # scalars are loss-path, gate stats read parameters.
                # Only values this tick already fetched: no new sync.
                nonfinite = {"loss": 0, "grad": 0, "param": 0}
                for k, v in fetched.items():
                    if not math.isfinite(v):
                        cause = ("grad" if k.endswith(("/r1", "/pl"))
                                 else "loss")
                        nonfinite[cause] += 1
                for k, v in (gate_stats or {}).items():
                    if not math.isfinite(v):
                        nonfinite["param"] += 1
                if any(nonfinite.values()):
                    reg = obs.get_registry()
                    for cause, n in nonfinite.items():
                        if n:
                            reg.counter(
                                f"train/nonfinite_{cause}_total").inc(n)
                    reg.counter("train/nonfinite_total").inc(
                        sum(nonfinite.values()))
                    log.write(
                        "WARNING: non-finite tick stats "
                        f"(kimg {cur_nimg / 1000:.1f}): "
                        + ", ".join(f"{c}={n}" for c, n
                                    in nonfinite.items() if n))
                if t.debug_nans:
                    from gansformer_tpu.utils.debug import check_finite_stats

                    check_finite_stats(
                        fetched, where=f"kimg {cur_nimg / 1000:.1f}")
                # Per-phase breakdown for THIS tick window.  Self times
                # (child-span time subtracted) partition covered wall
                # time, so the timing/phase/* values sum to ≈sec_per_tick
                # — the invariant tests/test_obs.py holds the loop to.
                phases = tracer.drain()
                data_wait_s = phases.get("data_wait", {}).get("total_s", 0.0)
                stats = {
                    "Progress/tick": tick,
                    "Progress/kimg": cur_nimg / 1000,
                    "timing/sec_per_tick": sec_per_tick,
                    "timing/img_per_sec": imgs_done / max(sec_per_tick, 1e-9),
                    "timing/img_per_sec_per_chip":
                        imgs_done / max(sec_per_tick, 1e-9) / n_chips,
                    # Absolute wait blocked in next(batches) this tick
                    # (VERDICT r5 item 8): the frac view hides magnitude
                    # when sec_per_tick itself moves; a starved device
                    # shows as seconds here on any future TPU run log.
                    "timing/data_wait_s": data_wait_s,
                    "timing/data_wait_frac":
                        data_wait_s / max(sec_per_tick, 1e-9),
                    **{f"timing/phase/{name}": v["self_s"]
                       for name, v in phases.items()},
                    **(gate_stats or {}),
                    **fetched,
                }
                if flops_per_it and imgs_done:
                    # sec per iteration × FLOPs per iteration vs chip peak;
                    # >1.0 would mean the clock is lying (PERF.md §1b).
                    sec_per_it = sec_per_tick / (imgs_done / t.batch_size)
                    stats["timing/mfu"] = (
                        flops_per_it / sec_per_it / (peak * 1e12))
                if sampler.sampling:
                    # The sampled window ends HERE (both endpoints are
                    # block_until_ready-synced, so busy-vs-wall is
                    # honest).  Folds device/* gauges before the
                    # registry snapshot below captures them.
                    dev = sampler.stop_and_fold(
                        wall_s=sec_per_tick,
                        iters=imgs_done / t.batch_size)
                    if dev is not None and dev.get("status") == "ok":
                        log.write(
                            "device sample: busy {:.0f} ms / wall "
                            "{:.0f} ms (ratio {:.2f}, {})".format(
                                dev["busy_s"] * 1e3, sec_per_tick * 1e3,
                                dev["busy_s"] / max(sec_per_tick, 1e-9),
                                dev["source"]))
                    elif dev is not None:
                        log.write("device sample unavailable: "
                                  f"{dev.get('reason', '?')[:200]}")
                if tick == 0:
                    retrace_watch.arm()    # warm-up compiles end here
                else:
                    retrace_watch.poll()
                log.log_tick(stats, telemetry=obs.get_registry().snapshot())
                heartbeat.beat(step=cur_nimg, kimg=cur_nimg / 1000)
                if jax.process_index() == 0:
                    obs.get_registry().write_prom(prom_path)
                # Async-writer failures surface HERE, one tick boundary
                # after the write started — after the tick's stats flushed
                # (the crash record stays readable) but before new side
                # work piles onto a dead writer.
                ckpt.check_error(ckpt_dir)
                if snap_writer is not None:
                    snap_writer.poll()
                # Fault-injection point (supervise/faults.py): the tick
                # boundary is where a scripted SIGTERM "preemption
                # notice" or SIGKILL lands deterministically.
                faults.fire("tick", tick=tick, step=cur_nimg)
                tick += 1
                tick_start_nimg = cur_nimg
                tick_start_time = time.time()

                if t.profile_dir and tick == 1 and not profiling:
                    jax.profiler.start_trace(t.profile_dir)
                    profiling = True
                    log.write(f"profiler: tracing the steady-state window "
                              f"logged as Progress/tick=1 → {t.profile_dir}")
                elif profiling:
                    jax.profiler.stop_trace()
                    profiling = False
                    log.write("profiler: trace complete (window: the tick "
                              "whose stats line above says Progress/tick=1)")
                elif cur_nimg < total_kimg * 1000:
                    # periodic device-truth sample: trace the WHOLE next
                    # tick window; stopped & folded at the next boundary
                    # (no-op unless the cadence fires — and never while
                    # the one-shot profile_dir trace owns the profiler)
                    sampler.maybe_start(tick)

                if t.image_snapshot_ticks and tick % t.image_snapshot_ticks == 0:
                    snapshot_images(state, cur_nimg / 1000)
                if t.snapshot_ticks and tick % t.snapshot_ticks == 0:
                    # Async (t.async_checkpoint): the loop thread pays
                    # O(dispatch) — a device-side state copy + D2H start —
                    # and the serialize/fsync/rename rides the single-slot
                    # writer thread (ckpt.py).  Safe to call from every
                    # process: only process 0 writes, and the path has no
                    # collectives, so there is no barrier to deadlock on.
                    with span("checkpoint"):
                        ckpt.save(ckpt_dir, state, cfg,
                                  block=not t.async_checkpoint)
                    log.write(f"checkpoint @ {cur_nimg / 1000:.1f} kimg")
                if t.metric_ticks > 0 and t.metrics and \
                        tick % t.metric_ticks == 0:
                    from gansformer_tpu.metrics.metric_base import FLAG_KEYS

                    with span("metric"):
                        results = run_metrics(state)
                    # Flags (calibrated regime, …) are state, not series:
                    # flag-<name>.txt + a log line, never metric-*.txt
                    # (VERDICT r5 weak #4 / item 7).
                    flags = {k: results.pop(k) for k in FLAG_KEYS
                             if k in results}
                    for name, val in results.items():
                        log.metric(name, val, cur_nimg / 1000)
                    for name, val in flags.items():
                        log.flag(name, val)
                    log.write("metrics @ {:.1f} kimg: {}{}".format(
                        cur_nimg / 1000,
                        {k: round(v, 3) for k, v in results.items()},
                        "".join(
                            "  [{}={}]".format(
                                k, int(v) if isinstance(
                                    v, (bool, int, float)) else v)
                            for k, v in flags.items())))
    finally:
        if profiling:
            jax.profiler.stop_trace()
        # discard (not fold) any in-flight device-time sample: the
        # process-global profiler must be released on every exit path
        sampler.close()
        # Close order matters: the host-side PrefetchIterator first (its
        # close() parks a sentinel that wakes a transfer thread blocked on
        # an empty host queue), then the DevicePrefetcher join.
        batches.close()
        if dev_batches is not None:
            dev_batches.close()
        # Release the dataset's cached record fds only after both
        # prefetch layers (its readers) have joined.
        dataset.close()
        # Join in-flight background writes WITHOUT re-raising: on the
        # exceptional path a writer failure must not mask the training
        # exception already unwinding (it resurfaces via wait() below on
        # the clean path).  Under preemption shutdown the joins are
        # bounded — a wedged (daemon) writer thread must not block the
        # exit past the grace window.
        if snap_writer is not None:
            snap_writer.wait(reraise=False,
                             timeout=preempt.shutdown_timeout_s)
        ckpt.wait(ckpt_dir, reraise=False,
                  timeout=preempt.shutdown_timeout_s)
        # final telemetry: whatever accumulated since the last tick still
        # reaches events.jsonl / telemetry.prom / the heartbeat, and the
        # heartbeat records the last step an aborted run reached.
        # phase="finalize": the post-loop final snapshot + synchronous
        # checkpoint follow with no tick beats — a supervisor must judge
        # that window against its startup grace (probe_hang), or a slow
        # final save would be killed as a hang seconds from completion.
        tracer.flush()
        heartbeat.beat(step=cur_nimg, kimg=cur_nimg / 1000,
                       extra={"phase": "finalize"})
        if jax.process_index() == 0:
            obs.get_registry().write_prom(prom_path)

    # final snapshot + checkpoint (skip a re-save of an already-saved step)
    snapshot_images(state, cur_nimg / 1000)
    if snap_writer is not None:
        snap_writer.wait()   # surface any snapshot-writer failure
    ckpt.wait(ckpt_dir)   # settle async saves before reading latest_step
    if ckpt.latest_step(ckpt_dir) != int(jax.device_get(state.step)):
        with span("checkpoint"):
            ckpt.save(ckpt_dir, state, cfg)
    log.write(f"done: {cur_nimg / 1000:.1f} kimg")
    tracer.flush()
    return state
