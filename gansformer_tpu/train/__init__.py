from gansformer_tpu.train.state import TrainState, create_train_state
from gansformer_tpu.train.steps import TrainStepFns, make_train_steps
from gansformer_tpu.train.loop import train
