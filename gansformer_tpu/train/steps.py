"""Jitted train steps — the SPMD replacement for the reference's hot loop.

Reference (SURVEY.md §3.1): per-GPU towers registered on two ``tflib.
Optimizer``\\ s, an NCCL all-reduce at ``apply_updates()``, and a Python
``sess.run`` pair per iteration, with lazy-reg variants of the train ops run
every N steps.

TPU-native design:
* ONE function per phase combination — ``(d, d+r1, g, g+pl)`` — each a
  separate jit specialization selected in Python by ``step % interval``
  (static dispatch; no recompile churn — SURVEY.md §7.3 item 2).
* Data parallelism is two annotations, not a subsystem: input batches
  arrive sharded over the ``data`` mesh axis, and the IN-STEP latent
  draws are constrained onto it too (``_sample_z`` — a replicated key
  alone would replicate all G compute; ISSUE 7); params replicated
  (opt-state optionally FSDP-sharded — ``pin_state_layout``); XLA turns
  the loss mean into a ``psum`` over ICI.  No gradient-all-reduce code
  exists anywhere.
* State is donated: params/opt-state buffers are updated in place in HBM.
* Style mixing (reference ``style_mixing_prob``) swaps a random suffix of
  latent components to a second mapping pass — implemented with a
  per-sample ``where`` mask (no data-dependent control flow under jit).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from gansformer_tpu.core.config import ExperimentConfig
from gansformer_tpu.data.dataset import normalize_images
from gansformer_tpu.losses.gan import (
    d_logistic_loss,
    g_nonsaturating_loss,
    path_length_penalty,
    r1_penalty,
    r1_slice,
)
from gansformer_tpu.models.discriminator import Discriminator
from gansformer_tpu.models.generator import Generator
from gansformer_tpu.parallel.mesh import (
    MeshEnv, ambient_data_size, constrain_data_axis)
from gansformer_tpu.train.state import TrainState, make_optimizers

Metrics = Dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class TrainStepFns:
    """The four jitted step functions + eval-time samplers."""

    d_step: Callable[[TrainState, Any, jax.Array], Tuple[TrainState, Metrics]]
    d_step_r1: Callable[[TrainState, Any, jax.Array], Tuple[TrainState, Metrics]]
    g_step: Callable[[TrainState, jax.Array], Tuple[TrainState, Metrics]]
    g_step_pl: Callable[[TrainState, jax.Array], Tuple[TrainState, Metrics]]
    # Fused lazy-reg cycle: ONE jitted program running ``cycle_len``
    # full (D, G) iterations — the reg variants at their cadence, the
    # plain iterations inside nested ``lax.scan`` so the compiled program
    # stays ~the size of the four phase programs, not cycle_len×.  One
    # host dispatch per cycle_len iterations: python/dispatch overhead
    # (and, on a tunneled backend, per-call RTT exposure) drops 32×.
    # ``None`` when d_reg_interval is not a multiple of g_reg_interval.
    # Signature: cycle(state, imgs [K,B,H,W,C], rng, it0, labels?) →
    # (state, aux_sums); per-key iteration counts are STATIC and live in
    # ``cycle_counts`` (host ints — keeping them out of the jit return
    # avoids per-dispatch device scalar traffic for trace-time constants).
    cycle: Optional[Callable]
    cycle_len: int
    cycle_counts: Dict[str, int]
    # Generator sampler (params, w_avg, z, rng, truncation_psi) — pass
    # ``ema_params`` for eval (the Gs path) or ``g_params`` for debug grids.
    sample: Callable[..., jax.Array]
    sample_train: Callable[..., jax.Array]    # alias of ``sample``
    # PPL probe (params, z0, z1, t, rng, epsilon) → (img_t, img_t+eps):
    # images at w-space lerp positions t and t+ε with SHARED noise — the
    # perceptual-path-length pair generator (metrics/ppl.py).
    ppl_pairs: Callable[..., Tuple[jax.Array, jax.Array]]


def _wrap_cycle(cycle_jit, wrapped):
    """The fused cycle's jit-boundary shim (module-level so the it0
    canonicalization is unit-testable without compiling the cycle)."""

    @functools.wraps(wrapped)
    def cycle_fn(state, imgs_k, rng, it0, label_k=None):
        # int() pins it0's trace-key flavor at the jit boundary: a
        # python int and an np.int32 of the same value hash to
        # different avals (weak vs strong dtype), and each flavor
        # would pay a full XLA compile of the largest program in the
        # repo (found by the retrace-hazard trace rule, ISSUE 4).
        # Tracers pass through: make_jaxpr/eval_shape trace this
        # wrapper too, and an abstract it0 cannot (and need not)
        # be concretized.
        if not isinstance(it0, jax.core.Tracer):
            it0 = int(it0)
        return cycle_jit(state, imgs_k, rng, it0, label_k)

    # bench.py compiles via lower(); the retrace probe reads the
    # trace-cache size — both live on the underlying jit object.
    cycle_fn.lower = cycle_jit.lower
    cycle_fn._cache_size = getattr(cycle_jit, "_cache_size", None)
    return cycle_fn


def _sample_z(cfg, rng, batch):
    """In-step latent draw, SHARDED onto the data mesh axis.

    The key is replicated (every device folds the same stream — the
    fused/unfused parity contract), so without the constraint the whole
    G compute downstream is replicated: N chips synthesize the same
    full batch and the compiled step has zero collectives (the ISSUE 7
    graftcomms finding).  The constraint makes GSPMD shard synthesis
    over ``data`` and turn the gradient mean into an all-reduce; values
    are unchanged, so mesh data=1 training is bit-identical."""
    m = cfg.model
    z = jax.random.normal(rng, (batch, m.num_ws, m.latent_dim), jnp.float32)
    return constrain_data_axis(z)


def apply_truncation(ws: jax.Array, w_avg: jax.Array,
                     truncation_psi: float) -> jax.Array:
    """The truncation trick (reference w_avg EMA + ψ cutoff, SURVEY.md
    §2.3) — THE definition; every sampler (jitted eval sampler, generate
    CLI, attention-overlay path) must go through it."""
    if truncation_psi == 1.0:
        return ws
    return w_avg[None, None, :] + truncation_psi * (ws - w_avg[None, None, :])


def make_train_steps(cfg: ExperimentConfig, env: Optional[MeshEnv] = None,
                     batch_size: Optional[int] = None) -> TrainStepFns:
    m, t = cfg.model, cfg.train
    G = Generator(m)
    D = Discriminator(m)
    g_tx, d_tx = make_optimizers(cfg)
    batch = batch_size if batch_size is not None else t.batch_size
    w_avg_beta = 0.995

    def pin_state_layout(st: TrainState) -> TrainState:
        """Pin the UPDATED state to the declared layout
        (parallel/contracts): params/EMA/stats replicated; opt moments
        replicated, or per-leaf on ``data`` under ``mesh.fsdp``.

        Two failure modes without the pin, both observed on a 2-device
        mesh: (a) with batch-sharded latents in the program, GSPMD may
        leave some updated-PARAM leaves sharded (deferring the gather)
        — the next dispatch then sees different input shardings, so an
        AOT-compiled step errors and a jit loop silently respecializes
        every step; (b) under fsdp the sharded Adam moments propagate
        forward through ``apply_updates`` and the new params/EMA come
        out sharded, breaking donation aliasing AND handing the next
        forward a full-param gather.  The pin makes the output layout
        the contract's — XLA gathers the per-leaf UPDATES instead (the
        declared ZeRO-1 cost under fsdp; a no-cost annotation when
        everything is already replicated).  Skipped without an ambient
        multi-device data axis, so single-device programs are
        byte-identical to the unpinned jaxpr."""
        n = ambient_data_size()
        if n <= 1:
            return st
        from jax.sharding import PartitionSpec as P

        from gansformer_tpu.parallel.contracts import (
            fsdp_spec, state_leaf_role)

        def pin(path, leaf):
            if not hasattr(leaf, "shape"):
                return leaf
            role = state_leaf_role(path)
            spec = (fsdp_spec(leaf.shape, n)
                    if cfg.mesh.fsdp and role == "opt_state" else P())
            return jax.lax.with_sharding_constraint(leaf, spec)

        return jax.tree_util.tree_map_with_path(pin, st)

    def ema_beta_at(step: jax.Array) -> jax.Array:
        """Per-step EMA decay from the half-life in kimg (reference
        ema_kimg), with the optional ramp-up cap (reference ema_rampup:
        half-life grows with cur_nimg early in training)."""
        ema_nimg = jnp.asarray(t.ema_kimg * 1000.0, jnp.float32)
        if t.ema_rampup is not None:
            ema_nimg = jnp.minimum(
                ema_nimg, step.astype(jnp.float32) * t.ema_rampup)
        return 0.5 ** (batch / jnp.maximum(ema_nimg, 1e-8))

    def g_forward(g_params, z, noise_rng, mix_rng=None, label=None):
        """Mapping (+ style mixing) + synthesis; returns (imgs, ws)."""
        ws = G.apply({"params": g_params}, z, label, method=Generator.map)
        if mix_rng is not None and t.style_mixing_prob > 0:
            k_z, k_cut, k_p = jax.random.split(mix_rng, 3)
            # second mapping pass rides the same batch sharding as the
            # primary latents (replicated key — see _sample_z)
            z2 = constrain_data_axis(
                jax.random.normal(k_z, z.shape, z.dtype))
            ws2 = G.apply({"params": g_params}, z2, label,
                          method=Generator.map)
            n, num_ws = ws.shape[0], ws.shape[1]
            # per-sample crossover component index; prob-gated
            cut = jax.random.randint(k_cut, (n, 1), 1, num_ws)
            do_mix = jax.random.uniform(k_p, (n, 1)) < t.style_mixing_prob
            comp = jnp.arange(num_ws)[None, :]
            mask = (comp >= cut) & do_mix                       # [n, num_ws]
            ws = jnp.where(mask[..., None], ws2, ws)
        imgs = G.apply({"params": g_params}, ws, rngs={"noise": noise_rng},
                       method=Generator.synthesize)
        return imgs, ws

    # ---------------- D steps ----------------

    def d_loss_fn(d_params, g_params, reals, z, rng, label, do_r1: bool):
        k_noise, k_mix = jax.random.split(jax.random.fold_in(rng, 1))
        # Fakes are conditioned on the real batch's labels (the lineage
        # samples G's training labels from the dataset distribution).
        fakes, _ = g_forward(g_params, z, k_noise, k_mix, label)
        fakes = jax.lax.stop_gradient(fakes)
        real_logits = D.apply({"params": d_params}, reals, label)
        fake_logits = D.apply({"params": d_params}, fakes, label)
        loss = d_logistic_loss(real_logits, fake_logits)
        aux = {
            "Loss/D": loss,
            "Loss/scores/real": jnp.mean(real_logits),
            "Loss/scores/fake": jnp.mean(fake_logits),
        }
        if do_r1:
            # r1_batch_shrink lever (default 1 = full batch): the penalty
            # rides a batch slice; the slice mean is unbiased so the
            # lazy-reg weight below stays as-is (losses/gan.py r1_slice).
            reals_r1 = r1_slice(reals, t.r1_batch_shrink)
            label_r1 = (None if label is None
                        else label[: reals_r1.shape[0]])
            r1 = r1_penalty(
                lambda x: D.apply({"params": d_params}, x, label_r1),
                reals_r1)
            aux["Loss/D/r1"] = r1
            # lazy reg: scale by interval so the *time-averaged* strength
            # matches an every-step penalty (reference trick).
            loss = loss + (t.r1_gamma * 0.5) * r1 * t.d_reg_interval
        return loss, aux

    def _d_step(state: TrainState, batch_imgs, rng, label=None, *,
                do_r1: bool):
        reals = normalize_images(batch_imgs)
        if cfg.data.mirror_augment:
            flip = jax.random.bernoulli(
                jax.random.fold_in(rng, 7), 0.5, (reals.shape[0], 1, 1, 1))
            reals = jnp.where(flip, reals[:, :, ::-1, :], reals)
        z = _sample_z(cfg, jax.random.fold_in(rng, 0), reals.shape[0])
        grad_fn = jax.value_and_grad(d_loss_fn, has_aux=True)
        (_, aux), grads = grad_fn(state.d_params, state.g_params, reals, z,
                                  rng, label, do_r1)
        # Adam bias correction divides by 1 - beta^t, which is positive
        # because optax increments count before use (t >= 1).
        updates, d_opt = d_tx.update(grads, state.d_opt, state.d_params)  # graftlint: disable=unstable-primitive
        d_params = optax.apply_updates(state.d_params, updates)
        return pin_state_layout(
            state.replace(d_params=d_params, d_opt=d_opt)), aux

    # ---------------- G steps ----------------

    def g_loss_fn(g_params, d_params, z, rng, pl_mean, label, do_pl: bool):
        k_noise, k_mix = jax.random.split(jax.random.fold_in(rng, 2))
        fakes, ws = g_forward(g_params, z, k_noise, k_mix, label)
        fake_logits = D.apply({"params": d_params}, fakes, label)
        loss = g_nonsaturating_loss(fake_logits)
        aux = {"Loss/G": loss}
        new_pl_mean = pl_mean
        if do_pl:
            # Reference shrinks the PL batch (pl_batch_shrink) to bound cost
            # and draws fresh latents for the probe.
            pl_batch = max(1, ws.shape[0] // max(1, t.pl_batch_shrink))
            k_pl, k_plnoise = jax.random.split(jax.random.fold_in(rng, 3))
            z_pl = _sample_z(cfg, k_pl, pl_batch)
            label_pl = None if label is None else label[:pl_batch]
            ws_pl = G.apply({"params": g_params}, z_pl, label_pl,
                            method=Generator.map)

            def synth(w):
                return G.apply({"params": g_params}, w,
                               rngs={"noise": jax.random.fold_in(rng, 4)},
                               method=Generator.synthesize)

            pl, new_pl_mean = path_length_penalty(
                synth, ws_pl, pl_mean, k_plnoise, t.pl_decay)
            aux["Loss/G/pl"] = pl
            loss = loss + t.pl_weight * pl * t.g_reg_interval
        w_batch_avg = jnp.mean(
            jax.lax.stop_gradient(ws).astype(jnp.float32), axis=(0, 1))
        return loss, (aux, new_pl_mean, w_batch_avg)

    def _g_step(state: TrainState, rng, label=None, *, do_pl: bool):
        z = _sample_z(cfg, jax.random.fold_in(rng, 5), batch)
        grad_fn = jax.value_and_grad(g_loss_fn, has_aux=True)
        (_, (aux, new_pl_mean, w_batch_avg)), grads = grad_fn(
            state.g_params, state.d_params, z, rng, state.pl_mean, label,
            do_pl)
        # Adam bias correction divides by 1 - beta^t, which is positive
        # because optax increments count before use (t >= 1).
        updates, g_opt = g_tx.update(grads, state.g_opt, state.g_params)  # graftlint: disable=unstable-primitive
        g_params = optax.apply_updates(state.g_params, updates)
        ema_beta = ema_beta_at(state.step)
        ema_params = jax.tree_util.tree_map(
            lambda e, p: e * ema_beta + p * (1.0 - ema_beta),
            state.ema_params, g_params)
        w_avg = state.w_avg * w_avg_beta + w_batch_avg * (1.0 - w_avg_beta)
        return pin_state_layout(state.replace(
            step=state.step + batch,   # step counts images (kimg accounting)
            g_params=g_params, g_opt=g_opt, ema_params=ema_params,
            w_avg=w_avg, pl_mean=new_pl_mean)), aux

    # ---------------- fused lazy-reg cycle ----------------

    d_reg, g_reg = t.d_reg_interval, t.g_reg_interval
    can_cycle = g_reg >= 1 and d_reg >= g_reg and d_reg % g_reg == 0

    def _cycle(state: TrainState, imgs_k, rng, it0, label_k=None):
        """cycle_len = d_reg iterations in one program.

        ``imgs_k``: [K, B, H, W, C] uint8 (K = d_reg); ``rng``: the loop's
        base key (PRNGKey(seed+4)); ``it0``: global iteration index of the
        first iteration (traced — resume-safe).  Per-iteration rng is
        ``fold_in(rng, it0 + i)``, identical to the unfused loop's
        derivation, so fused and unfused training follow the same random
        stream (held to parity in tests/test_train.py).
        """
        n_blocks = d_reg // g_reg

        def label_at(idx):
            return None if label_k is None else label_k[idx]

        def plain_body(st, idx):
            r = jax.random.fold_in(rng, it0 + idx)
            st, d_aux = _d_step(st, imgs_k[idx], jax.random.fold_in(r, 0),
                                label_at(idx), do_r1=False)
            st, g_aux = _g_step(st, jax.random.fold_in(r, 1), label_at(idx),
                                do_pl=False)
            return st, {**d_aux, **g_aux}

        def scan_plain(st, idxs):
            """(d, g) over a run of plain iterations; returns key-wise SUMS."""
            st, auxes = jax.lax.scan(plain_body, st, idxs)
            return st, jax.tree_util.tree_map(lambda a: a.sum(0), auxes)

        sums: Dict[str, jax.Array] = {}

        def add(aux: Dict[str, jax.Array], n_iters: int) -> None:
            del n_iters   # counts are static — see cycle_counts below
            for k, v in aux.items():
                sums[k] = sums[k] + v if k in sums else v

        # block 0 head: the full-reg pair (D+R1, G+PL), unrolled once
        r0 = jax.random.fold_in(rng, it0)
        st, d_aux = _d_step(state, imgs_k[0], jax.random.fold_in(r0, 0),
                            label_at(0), do_r1=True)
        st, g_aux = _g_step(st, jax.random.fold_in(r0, 1), label_at(0),
                            do_pl=True)
        add(d_aux, 1)
        add(g_aux, 1)
        if g_reg > 1:
            st, psum = scan_plain(st, jnp.arange(1, g_reg))
            add(psum, g_reg - 1)

        if n_blocks > 1:
            # blocks 1..n-1 share one structure — (D, G+PL) head + plain
            # run — so they ride an outer scan (nested scans keep the
            # compiled program size independent of d_reg).
            def block_body(st, k):
                base = k * g_reg
                r = jax.random.fold_in(rng, it0 + base)
                st, d_aux = _d_step(st, imgs_k[base],
                                    jax.random.fold_in(r, 0), label_at(base),
                                    do_r1=False)
                st, g_aux = _g_step(st, jax.random.fold_in(r, 1),
                                    label_at(base), do_pl=True)
                head = {**d_aux, **g_aux}
                if g_reg > 1:
                    st, psum = scan_plain(st, base + jnp.arange(1, g_reg))
                else:
                    psum = {}
                return st, (head, psum)

            st, (heads, psums) = jax.lax.scan(
                block_body, st, jnp.arange(1, n_blocks))
            add(jax.tree_util.tree_map(lambda a: a.sum(0), heads),
                n_blocks - 1)
            if g_reg > 1:
                add(jax.tree_util.tree_map(lambda a: a.sum(0), psums),
                    (n_blocks - 1) * (g_reg - 1))
        return st, sums

    # Static per-key iteration counts for the cycle's aux SUMS (matching
    # the loss functions' aux keys; the fused/unfused parity test asserts
    # these against counts observed from the real unfused loop, so a new
    # aux key cannot silently drift past this table).
    cycle_counts = {
        "Loss/D": d_reg, "Loss/scores/real": d_reg,
        "Loss/scores/fake": d_reg, "Loss/G": d_reg,
        "Loss/D/r1": 1, "Loss/G/pl": d_reg // g_reg,
    } if can_cycle else {}

    # ---------------- samplers ----------------

    def _sample(params, w_avg, z, rng, truncation_psi: float, label=None):
        ws = G.apply({"params": params}, z, label, method=Generator.map)
        ws = apply_truncation(ws, w_avg, truncation_psi)
        return G.apply({"params": params}, ws, rngs={"noise": rng},
                       method=Generator.synthesize)

    def _ppl_pairs(params, z0, z1, t, rng, epsilon: float, label=None):
        """w-space lerp endpoints for PPL: returns images at interpolation
        positions t and t+ε, with shared synthesis noise (the lineage's
        sampling='full', space='w' regime)."""
        w0 = G.apply({"params": params}, z0, label, method=Generator.map)
        w1 = G.apply({"params": params}, z1, label, method=Generator.map)
        tt = t[:, None, None]
        wa = w0 + (w1 - w0) * tt
        wb = w0 + (w1 - w0) * (tt + epsilon)
        img_a = G.apply({"params": params}, wa, rngs={"noise": rng},
                        method=Generator.synthesize)
        # same key on purpose: PPL measures the w-space perturbation alone,
        # so the pair must share its synthesis noise
        img_b = G.apply({"params": params}, wb, rngs={"noise": rng},  # graftlint: disable=rng-key-reuse
                        method=Generator.synthesize)
        return img_a, img_b

    donate_state = dict(donate_argnums=(0,))
    sample = jax.jit(_sample, static_argnames=("truncation_psi",))
    _ = env  # sharding comes from the inputs; env kept for API symmetry

    cycle_fn = _wrap_cycle(jax.jit(_cycle, **donate_state), _cycle) \
        if can_cycle else None

    def _named(name, fn, **kw):
        # jax.jit labels the PjitFunction trace events and the HloModule
        # after __name__; an anonymous partial traces as "<unnamed
        # function>", which would collapse all four phase variants into
        # one bucket in the device-time sampler's device/phase_ms/*
        # attribution (obs/device_time.py).
        p = functools.partial(fn, **kw)
        p.__name__ = name
        return p

    fns = TrainStepFns(
        d_step=jax.jit(_named("d_step", _d_step, do_r1=False),
                       **donate_state),
        d_step_r1=jax.jit(_named("d_step_r1", _d_step, do_r1=True),
                          **donate_state),
        g_step=jax.jit(_named("g_step", _g_step, do_pl=False),
                       **donate_state),
        g_step_pl=jax.jit(_named("g_step_pl", _g_step, do_pl=True),
                          **donate_state),
        cycle=cycle_fn,
        cycle_len=d_reg if can_cycle else 0,
        cycle_counts=cycle_counts,
        sample=sample,
        sample_train=sample,
        ppl_pairs=jax.jit(_ppl_pairs, static_argnames=("epsilon",)),
    )
    return fns


def make_metric_samplers(fns: TrainStepFns, state, cfg: ExperimentConfig,
                         env: MeshEnv, dataset,
                         truncation_psi: float = 1.0, seed: int = 7):
    """(sample_fn, pair_fn) for MetricGroup.run — the ONE place that knows
    how to drive the generator for metric sweeps: z/t/labels land sharded
    on the data mesh axis (the generator half of a 50k sweep is
    data-parallel, like the Inception half), batches are padded to mesh
    divisibility and trimmed, and conditional models draw labels from the
    dataset distribution.  Used by train/loop.py (per-tick metrics) and
    cli/evaluate.py (snapshot metrics)."""
    import numpy as np

    rng_holder = [jax.random.PRNGKey(seed)]

    # All z/t/label draws below are seeded identically on every process, so
    # env.put_global can assemble the sharded global batch from each host's
    # full copy — a plain device_put of a host-local array is NOT a valid
    # way to build a multi-host array (VERDICT r3 weak #3).

    def sample_fn(n):
        rng_holder[0], k1, k2, k3 = jax.random.split(rng_holder[0], 4)
        m = n + (-n) % env.data_size          # pad to mesh divisibility
        z = env.put_global(jax.random.normal(
            k1, (m, cfg.model.num_ws, cfg.model.latent_dim)))
        label = (dataset.random_labels(
            m, seed=int(jax.random.randint(k3, (), 0, 2**30)))
            if cfg.model.label_dim else None)
        if label is not None:
            label = env.put_global(label)
        return fns.sample(state.ema_params, state.w_avg, z, k2,
                          truncation_psi=truncation_psi, label=label)[:n]

    def pair_fn(n, ts, pair_seed, epsilon):
        k0, k1, kn = jax.random.split(jax.random.PRNGKey(pair_seed), 3)
        m = n + (-n) % env.data_size          # pad to mesh divisibility
        shape = (m, cfg.model.num_ws, cfg.model.latent_dim)
        ts = np.pad(np.asarray(ts, np.float32), (0, m - n))
        label = (dataset.random_labels(m, seed=pair_seed)
                 if cfg.model.label_dim else None)
        a, b = fns.ppl_pairs(
            state.ema_params,
            env.put_global(jax.random.normal(k0, shape)),
            env.put_global(jax.random.normal(k1, shape)),
            env.put_global(ts), kn, epsilon,
            None if label is None else env.put_global(label))
        return a[:n], b[:n]

    return sample_fn, pair_fn
