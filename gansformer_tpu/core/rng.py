"""PRNG-key plumbing.

The reference seeds a global TF1 graph RNG once in ``tflib.init_tf`` (SURVEY.md
§2.2 "TF session/bootstrap").  JAX is explicit-key; this module gives the rest
of the framework one small, consistent idiom for deriving named streams so
that runs are reproducible across host counts (fold in the process index only
where per-host streams are wanted, e.g. data augmentation).
"""

from __future__ import annotations

from typing import Dict, Iterator, Sequence

import jax


def key_for(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def split_named(key: jax.Array, names: Sequence[str]) -> Dict[str, jax.Array]:
    """Derive one independent stream per name (order-independent)."""
    return {name: jax.random.fold_in(key, _stable_hash(name)) for name in names}


def per_step(key: jax.Array, step) -> jax.Array:
    """Stream for a given training step (works under jit with traced step)."""
    return jax.random.fold_in(key, step)


def per_host(key: jax.Array) -> jax.Array:
    return jax.random.fold_in(key, jax.process_index())


def _stable_hash(name: str) -> int:
    # Python's hash() is salted per-process; use a tiny FNV-1a instead so the
    # same name maps to the same stream on every host.
    h = 2166136261
    for b in name.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def stream(key: jax.Array) -> Iterator[jax.Array]:
    """Infinite iterator of fresh keys (host-side loop use only)."""
    while True:
        key, sub = jax.random.split(key)
        yield sub
