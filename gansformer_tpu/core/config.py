"""Typed configuration layer.

The reference passes configuration as nested ``dnnlib.EasyDict`` objects built
by argparse in ``src/train.py`` and consumed as ``**kwargs`` by
``src/training/training_loop.py`` (SURVEY.md §5 "Config / flag system", T2).
Here that becomes frozen dataclasses — one per layer of the stack — plus named
presets mirroring the five driver benchmark configs at
/root/repo/BASELINE.json:7-11.  Everything is hashable/static so configs can be
closed over by ``jax.jit`` without retracing surprises.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Generator + discriminator architecture.

    Mirrors the capability surface of the reference's ``src/training/network.py``
    (G_GANsformer / D_GANsformer; SURVEY.md §2.3): StyleGAN2 skeleton with
    bipartite (simplex/duplex) attention between k latent components and the
    image feature grid.
    """

    resolution: int = 256
    img_channels: int = 3

    # --- latents -----------------------------------------------------------
    # k latent components attend to the image grid; one additional *global*
    # component (when use_global) drives the per-layer conv styles, matching
    # the reference's global latent that carries StyleGAN2-style modulation.
    components: int = 16
    latent_dim: int = 512
    w_dim: int = 512
    use_global: bool = True
    # Conditional generation (reference: optional ``.labels`` file next to
    # the TFRecords, SURVEY.md §2.2 dataset reader row).  0 = unconditional.
    # When >0: G embeds the label into every mapping input; D scores via a
    # projection head (logit = ⟨features, embed(label)⟩).
    label_dim: int = 0

    # --- mapping network ---------------------------------------------------
    mapping_layers: int = 8
    mapping_dim: int = 512
    mapping_lrmul: float = 0.01

    # --- synthesis ---------------------------------------------------------
    fmap_base: int = 16384
    fmap_max: int = 512
    fmap_min: int = 1
    # 'none' | 'simplex' | 'duplex'  (SURVEY.md §2.3)
    attention: str = "duplex"
    # Bipartite attention is applied at block resolutions
    # attn_start_res..attn_max_res (cost is O(n*k), n = H*W — linear in
    # pixels, the GANsformer scaling property to preserve; SURVEY.md §5
    # "Long-context").  Default 4: the reference attends "from 4x4 up"
    # (SURVEY.md §2.3) — at n=16 the block costs almost nothing.
    attn_start_res: int = 4
    attn_max_res: int = 128
    num_heads: int = 1
    # 'add' | 'mul' | 'both' — how attention output updates the grid features.
    integration: str = "both"
    # Where conv modulation styles come from (SURVEY.md §3.2 shows
    # ``modulated_conv2d(x, w_attn)`` — style derived from attention output):
    #   'global'    — every conv is styled by the global latent only; the k
    #                 components act region-wise through attention gating.
    #   'attention' — convs after an attention block are styled by the global
    #                 latent PLUS a learned projection of the refined latents
    #                 (the reference's attention-driven styling).
    style_mode: str = "global"
    pos_encoding: str = "sinusoidal"  # 'sinusoidal' | 'learned' | 'none'
    # Duplex: latents first update themselves from the grid (k-means-like
    # centroid step), then the grid attends back.
    kmeans_iters: int = 1
    # Sequence/context parallelism: shard the n = H·W grid axis of every
    # attention block over the mesh's model axis (SURVEY.md §2.4 SP row).
    # Needs mesh.model > 1 and an ambient ``jax.sharding.set_mesh``; the
    # trainer and dryrun arrange both.
    sequence_parallel: bool = False
    # 'xla' | 'pallas' — attention compute backend.  'pallas' uses the
    # fused blockwise kernels (ops/pallas_attention.py), differentiable to
    # second order since ISSUE 9, so it is valid for BOTH the forward-only
    # paths (generate/evaluate --attention-backend) and the four training
    # step programs (cli/train.py --attention-backend).  On TPU the first
    # use runs the native smoke check (fwd + bwd kernels) and the CLIs
    # fall back to 'xla' with the printed reason if Mosaic lowering fails.
    attention_backend: str = "xla"
    # 'xla' | 'pallas' — modulated-conv/upfirdn compute backend (ISSUE 14,
    # the last StyleGAN2 custom-op family): 'pallas' runs the fused
    # modulate→conv→demodulate, polyphase up-conv + depth-to-space, and
    # pad→FIR→resample kernels (ops/pallas_modconv.py,
    # ops/pallas_upfirdn.py), each with hand-written backward kernels
    # under custom_vjp — training-grade to second order, mirroring
    # attention_backend.  On TPU the first use runs the conv-family
    # native smoke check (fwd + bwd) and the CLIs fall back to 'xla'
    # with the printed reason if Mosaic lowering fails.
    conv_backend: str = "xla"
    # MFU lever (ISSUE 5, default OFF): fuse the attention K/V projections
    # into ONE matmul per direction — the duplex centroid phase's k_x/v_x
    # both project the n = H·W grid (the expensive read at 128²), and the
    # main phase's k_y/v_y both project the latents.  Mathematically exact
    # (concatenated weight columns; parity-tested in tests/test_levers.py);
    # the win, if any, is dispatch count + one grid read instead of two —
    # FLOPs are identical, so only the on-chip A/B (scripts/ab_levers.py)
    # can price it.  Changes the param tree: not checkpoint-compatible
    # with the unfused layout.
    attn_fused_kv: bool = False
    # NO remat flag, deliberately: per-block jax.checkpoint was measured to
    # INCREASE g_step_pl temp workspace at ffhq1024/batch-8 (16.85 →
    # 21.20 GiB) — second-order PL grads recompute through the checkpoint
    # boundary worse than XLA's own scheduling.  Measured result recorded
    # in PERF.md §2a; revisit only with a profile in hand.

    # --- discriminator -----------------------------------------------------
    mbstd_group_size: int = 4
    mbstd_num_features: int = 1
    d_attention: bool = False
    d_components: int = 16  # learned query vectors when d_attention

    # --- numerics ----------------------------------------------------------
    # Compute dtype for conv/matmul-heavy paths; params stay fp32.
    dtype: str = "float32"  # 'float32' | 'bfloat16'
    blur_filter: Tuple[int, ...] = (1, 3, 3, 1)

    @property
    def resolution_log2(self) -> int:
        r = self.resolution.bit_length() - 1
        assert self.resolution == 2**r and self.resolution >= 4
        return r

    @property
    def num_ws(self) -> int:
        """Total latent components fed to mapping (k + optional global)."""
        return self.components + (1 if self.use_global else 0)

    def nf(self, res: int) -> int:
        """Feature maps at a given block resolution (StyleGAN2 fmap schedule)."""
        stage = res.bit_length() - 1  # log2(res)
        return int(min(max(self.fmap_base // (2**stage), self.fmap_min), self.fmap_max))

    @property
    def block_resolutions(self) -> Tuple[int, ...]:
        return tuple(2**i for i in range(2, self.resolution_log2 + 1))

    def attn_resolutions(self) -> Tuple[int, ...]:
        if self.attention == "none":
            return ()
        return tuple(
            r
            for r in self.block_resolutions
            if self.attn_start_res <= r <= self.attn_max_res
        )


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training dynamics — two-timescale G/D with lazy regularization.

    Capability parity with the reference's ``src/training/training_loop.py`` +
    ``src/training/loss.py`` (SURVEY.md §2.2): alternating G/D Adam steps,
    lazy R1 on D every ``d_reg_interval`` steps, lazy path-length on G every
    ``g_reg_interval`` steps, EMA generator with ~10k-img half-life.
    """

    batch_size: int = 32            # global batch across the data mesh axis
    total_kimg: int = 25000
    g_lr: float = 2e-3
    d_lr: float = 2e-3
    adam_beta1: float = 0.0
    adam_beta2: float = 0.99
    adam_eps: float = 1e-8

    r1_gamma: float = 10.0
    d_reg_interval: int = 16
    g_reg_interval: int = 4
    # MFU lever (ISSUE 5): compute R1 on the first batch/r1_batch_shrink
    # reals only.  The slice mean is an unbiased estimator of the batch
    # mean, so the (γ/2)·interval lazy-reg weight needs NO further
    # compensation — only the estimator's variance grows.  Default 1 =
    # OFF (reference semantics); acceptance contract in tests/test_levers.
    r1_batch_shrink: int = 1
    pl_weight: float = 2.0
    pl_decay: float = 0.01
    # StyleGAN2's own PL cost bound (reference pl_batch_shrink): the PL
    # probe synthesizes batch/pl_batch_shrink fresh samples.  2 is the
    # reference default (the measured BASELINE); 1 = full-batch probe
    # (the expectation-parity reference), 4 = the prepared step-time
    # variant scripts/ab_levers.py prices against it on chip.
    pl_batch_shrink: int = 2
    style_mixing_prob: float = 0.9

    ema_kimg: float = 10.0
    ema_rampup: Optional[float] = None

    # Fused lazy-reg cycle: dispatch ONE jitted program per d_reg_interval
    # iterations (reg variants at their cadence inside, plain iterations
    # in nested lax.scan) instead of 2 dispatches per iteration — 32× less
    # host/dispatch overhead on the hot loop (train/steps.py ``cycle``).
    # Requires d_reg_interval % g_reg_interval == 0.  Device-side input
    # grows to d_reg_interval stacked batches (uint8: ~25 MB for the
    # ffhq256 flagship at batch 8).
    fused_cycle: bool = False

    # Async writeback (ISSUE 2 overlap layer): checkpoint saves, image
    # snapshots, and the tick-boundary stat fetch ride background
    # device→host copies + a bounded single-slot writer thread, so the
    # loop thread only pays dispatch cost.  Off = fully synchronous
    # writes on the loop thread (the parity/debug fallback).
    async_checkpoint: bool = True

    # cadence (ticks are the reference's unit of logging/checkpointing)
    kimg_per_tick: int = 4
    snapshot_ticks: int = 10
    image_snapshot_ticks: int = 10
    # in-loop metric runs every metric_ticks (reference: per-snapshot FID).
    # ``metrics`` is a comma list ('fid10k,is10k'); empty = disabled (run
    # cli/evaluate.py per checkpoint instead).
    metric_ticks: int = 50
    metrics: str = ""

    seed: int = 0

    # Debug switch (SURVEY.md §5 sanitizer row): enables jax_debug_nans +
    # per-tick finite checks on the fetched loss scalars.
    debug_nans: bool = False
    # Profiling (SURVEY.md §5 tracing row): jax.profiler trace of tick 1
    # (steady state — past all compiles) written here for TensorBoard's
    # profile plugin.  None = off.
    profile_dir: Optional[str] = None
    # Device-truth sampling (ISSUE 8): every N ticks, wrap one full tick
    # window in a jax.profiler trace, parse it (utils/profparse.py), and
    # fold device/* gauges into telemetry (device-time MFU, per-program
    # device ms, the wall-vs-device divergence ratio).  0 = off.  The
    # default cadence (1 tick traced in 8) keeps the amortized overhead
    # small; unattended relayed-TPU runs should pass 0 — a client killed
    # mid-trace was observed to wedge the tunnel's backend claim
    # (bench.py r4 note).  Mutually exclusive with profile_dir at
    # runtime: the one-shot trace owns the (process-global) profiler.
    device_time_ticks: int = 8


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset pipeline config (reference: src/training/dataset.py, SURVEY §2.2)."""

    name: str = "synthetic"
    path: Optional[str] = None      # directory of records / images
    resolution: int = 256
    channels: int = 3
    # 'synthetic' generates deterministic smooth images for smoke tests,
    # 'tfrecord' reads the reference's multi-resolution TFRecord format,
    # 'npz' reads a packed numpy archive.
    source: str = "synthetic"
    shuffle_buffer: int = 4096
    prefetch: int = 2
    # Device-resident input prefetch (ISSUE 2 overlap layer): a background
    # thread device_puts batches onto the mesh and keeps a small ring of
    # them already in HBM, collapsing the loop's h2d phase to a queue pop.
    # Off = synchronous device_put on the loop thread (parity fallback).
    device_prefetch: bool = True
    device_prefetch_depth: int = 2   # HBM ring size, in batches
    mirror_augment: bool = False
    # --- fault tolerance (ISSUE 15, docs/data.md) ---------------------------
    # Corruption budget: corrupt TFRecord records are QUARANTINED (ledger
    # + data/corrupt_records_total) and the run keeps streaming; it fails
    # typed (DataCorrupt → exit EXIT_DATA_CORRUPT, supervisor cause
    # 'data-corrupt', non-retryable) only once quarantined/total exceeds
    # this fraction — a static defect must not burn the restart budget.
    max_corrupt_frac: float = 0.01
    # Transient read errors (network filesystems) retry this many times
    # under exponential backoff before surfacing as a crash.
    io_retries: int = 3
    io_retry_base_s: float = 0.05
    # Producer-progress stall watchdog on the prefetch layers: a consumer
    # blocked this long with NO producer progress raises typed
    # DataStalled (exit EXIT_DATA_STALLED, supervisor cause 'data-stall')
    # — a fast classified data-hang signal well inside the supervisor's
    # 300 s heartbeat-staleness SIGKILL.  Must exceed the worst-case
    # single-batch decode; 0 disables the watchdog.
    stall_after_s: float = 120.0


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout.

    The reference's distribution story is in-graph GPU towers + NCCL
    all-reduce (SURVEY.md §2.4).  Here the whole backend collapses to a
    ``jax.sharding.Mesh`` with named axes; gradients ride XLA ``psum`` over
    ICI/DCN.  ``data`` is the only axis the GANsformer workload needs; a
    ``model`` axis hook is kept for forward-compatibility.
    """

    data: int = -1   # -1: use all visible devices
    model: int = 1
    # FSDP mode (ISSUE 7): shard optimizer-state leaves per-leaf over
    # the data axis (parallel/contracts.fsdp_spec — ZeRO-1).  Params,
    # EMA, and stats stay replicated, so forward/backward never pays a
    # parameter gather; the step pays per-leaf all-gathers of the
    # Adam UPDATES instead (priced in the collective-flow table).
    # Cuts the per-chip replicated opt-state footprint (~2x params per
    # optimizer) by the data-axis factor.  Default off — a data=1 mesh
    # makes it a no-op and the replicated layout stays bit-identical.
    fsdp: bool = False
    # multi-host process group (jax.distributed.initialize) parameters
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    def axis_sizes(self, n_devices: int) -> Tuple[int, int]:
        data = self.data if self.data > 0 else max(1, n_devices // self.model)
        return (data, self.model)


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    name: str = "default"
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    def validate(self) -> "ExperimentConfig":
        """Fail fast with a clear message instead of a deep trace-time
        assert (the reference validates nothing; SURVEY.md §5 config row
        asks for typed configs WITH validation).  Returns self for
        chaining."""
        m, t = self.model, self.train
        errs = []
        if m.resolution < 8 or m.resolution & (m.resolution - 1):
            errs.append(f"model.resolution must be a power of two ≥ 8, "
                        f"got {m.resolution}")
        if m.attention not in ("none", "simplex", "duplex"):
            errs.append(f"model.attention must be none|simplex|duplex, "
                        f"got {m.attention!r}")
        if m.style_mode not in ("global", "attention"):
            errs.append(f"model.style_mode must be global|attention, "
                        f"got {m.style_mode!r}")
        if m.integration not in ("add", "mul", "both"):
            errs.append(f"model.integration must be add|mul|both, "
                        f"got {m.integration!r}")
        if m.attention_backend not in ("xla", "pallas"):
            # Both backends are training-grade: the pallas kernels carry
            # backward kernels + a second-order derivative rule (ISSUE 9;
            # ops/pallas_attention.py).  On TPU the train CLI resolves
            # 'pallas' through the native smoke check first and falls
            # back to 'xla' with the reason if it fails.
            errs.append(f"model.attention_backend must be xla|pallas, "
                        f"got {m.attention_backend!r}")
        if m.attention_backend == "pallas" and m.sequence_parallel:
            # The pallas_call has no sharding rule: on a grid sharded over
            # the model axis GSPMD would all-gather the full n axis per
            # device, silently un-doing exactly the memory bound
            # sequence_parallel exists for.  Reject until a sharded kernel
            # path exists (shard_map over the n grid).
            errs.append("model.attention_backend='pallas' does not yet "
                        "have a sequence-parallel (model-axis-sharded) "
                        "kernel path; use attention_backend='xla' with "
                        "sequence_parallel, or drop sequence_parallel")
        if m.conv_backend not in ("xla", "pallas"):
            # Mirrors attention_backend exactly: both values are
            # training-grade (the pallas conv kernels carry backward
            # kernels + second-order rules, ISSUE 14); a typo must fail
            # here with the allowed set, not deep inside a trace.
            errs.append(f"model.conv_backend must be xla|pallas, "
                        f"got {m.conv_backend!r}")
        if m.conv_backend == "pallas" and m.sequence_parallel:
            # Same reasoning as the attention_backend rule above: a
            # pallas_call has no sharding rule, so a model-axis-sharded
            # grid would be silently all-gathered per device before
            # every conv kernel — un-doing the memory bound sequence
            # parallelism exists for.
            errs.append("model.conv_backend='pallas' does not yet have "
                        "a sequence-parallel (model-axis-sharded) kernel "
                        "path; use conv_backend='xla' with "
                        "sequence_parallel, or drop sequence_parallel")
        if m.dtype not in ("float32", "bfloat16"):
            errs.append(f"model.dtype must be float32|bfloat16, "
                        f"got {m.dtype!r}")
        if m.attention != "none" and m.attn_start_res > m.attn_max_res:
            errs.append(f"attn_start_res ({m.attn_start_res}) > "
                        f"attn_max_res ({m.attn_max_res})")
        if m.components < 1:
            errs.append(f"model.components must be ≥ 1, got {m.components}")
        if t.batch_size < 1:
            errs.append(f"train.batch_size must be ≥ 1, got {t.batch_size}")
        if t.pl_batch_shrink < 1:
            errs.append(f"pl_batch_shrink must be ≥ 1, got "
                        f"{t.pl_batch_shrink} (1 = full-batch probe)")
        elif t.batch_size % t.pl_batch_shrink:
            errs.append(f"pl_batch_shrink ({t.pl_batch_shrink}) must divide "
                        f"batch_size ({t.batch_size})")
        if t.device_time_ticks < 0:
            errs.append(f"device_time_ticks must be ≥ 0 (0 = off), got "
                        f"{t.device_time_ticks}")
        if t.r1_batch_shrink < 1:
            errs.append(f"r1_batch_shrink must be ≥ 1, got "
                        f"{t.r1_batch_shrink}")
        elif t.batch_size % t.r1_batch_shrink:
            errs.append(f"r1_batch_shrink ({t.r1_batch_shrink}) must divide "
                        f"batch_size ({t.batch_size}) — the R1 slice would "
                        f"silently truncate")
        # Divisibility failures most likely on a pod (ADVICE r3): catch them
        # here with a clear message instead of an opaque sharding error at
        # the first device_put / a trace-time reshape failure in mbstd.
        if self.mesh.data > 0 and t.batch_size % self.mesh.data:
            errs.append(f"train.batch_size ({t.batch_size}) must be "
                        f"divisible by mesh.data ({self.mesh.data}) — each "
                        f"data-axis row takes an equal batch shard")
        if t.fused_cycle and (t.g_reg_interval < 1 or t.d_reg_interval
                              % t.g_reg_interval):
            errs.append(
                f"train.fused_cycle needs d_reg_interval "
                f"({t.d_reg_interval}) to be a multiple of g_reg_interval "
                f"({t.g_reg_interval})")
        if self.data.device_prefetch and self.data.device_prefetch_depth < 1:
            errs.append(f"data.device_prefetch_depth must be ≥ 1, got "
                        f"{self.data.device_prefetch_depth}")
        if not 0.0 <= self.data.max_corrupt_frac <= 1.0:
            errs.append(f"data.max_corrupt_frac must be in [0, 1], got "
                        f"{self.data.max_corrupt_frac}")
        if self.data.io_retries < 0:
            errs.append(f"data.io_retries must be ≥ 0, got "
                        f"{self.data.io_retries}")
        if self.data.io_retry_base_s <= 0:
            errs.append(f"data.io_retry_base_s must be > 0, got "
                        f"{self.data.io_retry_base_s}")
        if self.data.stall_after_s < 0:
            errs.append(f"data.stall_after_s must be ≥ 0 (0 = watchdog "
                        f"off), got {self.data.stall_after_s}")
        if m.mbstd_group_size > 1 and t.batch_size % m.mbstd_group_size:
            # minibatch_stddev would silently shrink the group; surface the
            # mismatch instead so the trained config means what it says.
            errs.append(
                f"train.batch_size ({t.batch_size}) must be divisible by "
                f"model.mbstd_group_size ({m.mbstd_group_size}) — the "
                f"stddev layer would silently use a smaller group")
        if self.mesh.fsdp and self.mesh.data == 1:
            errs.append("mesh.fsdp with mesh.data=1 — there is no data "
                        "axis to shard optimizer state over; drop --fsdp "
                        "or grow the data axis")
        if self.mesh.fsdp and (self.mesh.coordinator_address is not None
                               or (self.mesh.num_processes or 1) > 1):
            errs.append("mesh.fsdp is single-host for now: the npz "
                        "checkpoint path gathers state to one process "
                        "(multi-host sharded checkpointing is ROADMAP "
                        "item 5); drop --fsdp or the multi-host flags")
        if self.mesh.model > 1 and not m.sequence_parallel:
            errs.append("mesh.model > 1 without model.sequence_parallel — "
                        "the model axis would idle; set sequence_parallel "
                        "or mesh.model=1")
        if m.sequence_parallel and self.mesh.model <= 1:
            errs.append("model.sequence_parallel needs mesh.model > 1")
        if errs:
            raise ValueError("invalid config:\n  - " + "\n  - ".join(errs))
        return self

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "ExperimentConfig":
        d = json.loads(s)
        return ExperimentConfig(
            name=d["name"],
            model=ModelConfig(**{k: tuple(v) if isinstance(v, list) else v
                                 for k, v in d["model"].items()}),
            train=TrainConfig(**d["train"]),
            data=DataConfig(**d["data"]),
            mesh=MeshConfig(**d["mesh"]),
        )


def _preset(name, model, train, data) -> ExperimentConfig:
    return ExperimentConfig(name=name, model=model, train=train, data=data)


# The five driver benchmark configs (/root/repo/BASELINE.json:7-11).
PRESETS = {
    # 1. CLEVR 64×64, Simplex, k=8, batch=4 — single-process CPU smoke.
    "clevr64-simplex": _preset(
        "clevr64-simplex",
        ModelConfig(resolution=64, components=8, attention="simplex",
                    attn_max_res=32, fmap_base=2048, fmap_max=256,
                    latent_dim=128, w_dim=128, mapping_dim=128,
                    mapping_layers=4),
        TrainConfig(batch_size=4, total_kimg=100, kimg_per_tick=1,
                    r1_gamma=1.0),
        DataConfig(name="clevr", resolution=64, source="synthetic"),
    ),
    # 2. FFHQ 256×256, Duplex, k=16 — paper headline config (north star).
    "ffhq256-duplex": _preset(
        "ffhq256-duplex",
        ModelConfig(resolution=256, components=16, attention="duplex",
                    attn_max_res=128, dtype="bfloat16",
                    style_mode="attention"),
        TrainConfig(batch_size=32, total_kimg=25000, r1_gamma=10.0),
        DataConfig(name="ffhq", resolution=256, source="tfrecord"),
    ),
    # 3. LSUN-Bedroom 256×256, Duplex, k=16.
    "bedroom256-duplex": _preset(
        "bedroom256-duplex",
        ModelConfig(resolution=256, components=16, attention="duplex",
                    attn_max_res=128, dtype="bfloat16",
                    style_mode="attention"),
        TrainConfig(batch_size=32, total_kimg=25000, r1_gamma=100.0),
        DataConfig(name="lsun-bedroom", resolution=256, source="tfrecord"),
    ),
    # 4. Cityscapes 256×256, Duplex, k=32 (compositional scenes).
    "cityscapes256-duplex": _preset(
        "cityscapes256-duplex",
        ModelConfig(resolution=256, components=32, attention="duplex",
                    attn_max_res=128, dtype="bfloat16",
                    style_mode="attention"),
        TrainConfig(batch_size=32, total_kimg=25000, r1_gamma=20.0),
        DataConfig(name="cityscapes", resolution=256, source="tfrecord"),
    ),
    # 5. FFHQ 1024×1024, Duplex — data-parallel across a v4-32 ICI mesh.
    "ffhq1024-duplex": _preset(
        "ffhq1024-duplex",
        ModelConfig(resolution=1024, components=16, attention="duplex",
                    attn_max_res=128, dtype="bfloat16",
                    style_mode="attention"),
        TrainConfig(batch_size=32, total_kimg=25000, r1_gamma=32.0),
        DataConfig(name="ffhq", resolution=1024, source="tfrecord"),
    ),
}


def get_preset(name: str) -> ExperimentConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name]
