from gansformer_tpu.core.config import (
    DataConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
    ExperimentConfig,
    PRESETS,
    get_preset,
)
