"""Input pipeline — host-side dataset readers feeding the device mesh.

Capability parity with the reference's ``src/training/dataset.py``
(``TFRecordDataset``: multi-resolution TFRecords, shuffle/prefetch, optional
labels; SURVEY.md §2.2/§3.4).  Re-designed for the JAX/TPU input model:

* The reference builds a ``tf.data`` graph wired *into* the TF1 training
  graph.  Under JAX the input pipeline is host-side Python/numpy producing
  per-process batch shards that the train loop ``device_put``\\ s onto the
  ``data`` mesh axis (SURVEY.md §7.3 item 6: per-host shard of records, no
  cross-host shuffle).
* Images flow as NHWC uint8 on the host and are normalized to [-1, 1] float
  on device (saves 4x host→device bandwidth vs shipping f32 — HBM/PCIe
  friendly).
* ``TFRecordDataset`` reads the reference's record format
  (``<name>-r{lod}.tfrecords``, features: shape [3] int64 + data bytes,
  CHW uint8) so datasets prepared for the reference work unchanged — via a
  hand-rolled TFRecord framing + protobuf walk, so the framework has NO
  TensorFlow dependency.  Since ISSUE 15 the source is **index-addressed**
  and fault-tolerant: the full matched-resolution shard set is read (not
  one file), a per-file record-offset index sidecar makes every record
  seekable (``start_batch`` resume advances the RNG stream only — the
  strict tick-parity contract now covers TFRecords), corrupt records are
  *quarantined* under a budget instead of killing the run, and transient
  read errors retry under bounded backoff (docs/data.md).
"""

from __future__ import annotations

import glob
import os
import queue
import re
import struct
import threading
import time
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from gansformer_tpu.data.errors import DataCorrupt, stall_guarded_get
from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.supervise import faults


class Dataset:
    """Iterator protocol: ``batches(batch_size)`` yields dicts with
    ``image`` [N,H,W,C] uint8 and optional ``label`` [N,label_dim] f32."""

    resolution: int
    channels: int
    has_labels: bool = False
    label_dim: int = 0
    num_images: Optional[int] = None

    def batches(self, batch_size: int, seed: int = 0,
                shard: Tuple[int, int] = (0, 1),
                start_batch: int = 0) -> Iterator[dict]:
        """Infinite batch stream.  ``start_batch`` positions the stream
        at batch index N of the seed-determined sequence — the resume
        contract: a run restored at iteration N consumes the same
        batches an uninterrupted run would, so loss trajectories stay
        tick-for-tick IDENTICAL across restarts.  Every source is
        index-addressed (synthetic/npz/folder/tfrecord since ISSUE 15's
        record-offset sidecar), so the fast-forward advances the RNG
        stream only — no image decode, no best-effort carve-outs."""
        raise NotImplementedError

    def set_quarantine_ledger(self, path: str) -> None:
        """Point the source's corruption-quarantine ledger at
        ``<run_dir>/data_quarantine.jsonl`` (the train loop wires this).
        Sources without a quarantine path (synthetic/npz/folder decode
        from trusted memory) ignore it."""

    def close(self) -> None:
        """Release OS resources (cached record fds).  Idempotent; a
        no-op for in-memory sources.  The train loop's finally calls
        it after the prefetch layers have joined."""

    def random_labels(self, n: int, seed: int = 0) -> Optional[np.ndarray]:
        """n labels drawn from the dataset's label distribution (reference
        ``get_random_labels``) — for conditional sampling at eval/snapshot
        time.  None for unconditional datasets."""
        labels = getattr(self, "labels", None)
        if labels is None:
            return None
        rs = np.random.RandomState(seed)
        return labels[rs.randint(0, len(labels), size=n)]

    def cache_tag(self) -> str:
        """Stable identity for disk caches (e.g. FID real-stats) — must
        distinguish different datasets, not just different classes."""
        src = getattr(self, "path", None) or getattr(self, "file", None) or ""
        return f"{self.__class__.__name__}-{src}-{self.resolution}"


class SyntheticDataset(Dataset):
    """Deterministic procedural images for smoke tests and CI.

    Replaces nothing in the reference (it has no test data story — SURVEY.md
    §4); exists so the end-to-end slice runs with zero downloads.  Produces
    smooth multi-scale Gabor-ish blobs with enough structure that D can
    learn *something* and FID-on-synthetic is a meaningful pipeline test.
    """

    def __init__(self, resolution: int = 64, channels: int = 3,
                 num_images: int = 10000):
        self.resolution = resolution
        self.channels = channels
        self.num_images = num_images

    def _make(self, idx: np.ndarray) -> np.ndarray:
        r, c = self.resolution, self.channels
        yy, xx = np.mgrid[0:r, 0:r].astype(np.float32) / r  # [r,r]
        imgs = np.empty((len(idx), r, r, c), dtype=np.uint8)
        for i, seed in enumerate(idx):
            rs = np.random.RandomState(int(seed) % (2**31))
            img = np.zeros((r, r, c), np.float32)
            for _ in range(4):
                fx, fy = rs.uniform(1, 6, 2)
                px, py = rs.uniform(0, 2 * np.pi, 2)
                cx, cy = rs.uniform(0.2, 0.8, 2)
                sig = rs.uniform(0.1, 0.4)
                blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * sig**2)))
                wave = np.sin(2 * np.pi * (fx * xx + px)) * np.sin(
                    2 * np.pi * (fy * yy + py))
                col = rs.uniform(-1, 1, c).astype(np.float32)
                img += (blob * wave)[..., None] * col
            img = np.tanh(img)
            imgs[i] = ((img * 0.5 + 0.5) * 255).astype(np.uint8)
        return imgs

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        for _ in range(start_batch):   # advance the index stream only
            rs.randint(0, self.num_images, size=batch_size)
        while True:
            idx = rs.randint(0, self.num_images, size=batch_size)
            idx = idx * num_shards + shard_id  # disjoint streams per host
            yield {"image": self._make(idx)}


class NpzDataset(Dataset):
    """Packed numpy archive: ``images`` [N,H,W,C] uint8 (+ optional
    ``labels``).  The fast path for small datasets (CIFAR/CLEVR-scale)."""

    def __init__(self, path: str):
        self.path = path
        with np.load(path) as z:
            self.images = z["images"]
            self.labels = z["labels"].astype(np.float32) if "labels" in z else None
        assert self.images.dtype == np.uint8 and self.images.ndim == 4
        self.resolution = self.images.shape[1]
        self.channels = self.images.shape[3]
        self.num_images = len(self.images)
        self.has_labels = self.labels is not None
        self.label_dim = 0 if self.labels is None else self.labels.shape[1]

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        local = np.arange(shard_id, self.num_images, num_shards)
        for _ in range(start_batch):   # advance the index stream only
            rs.randint(0, len(local), size=batch_size)
        while True:
            idx = local[rs.randint(0, len(local), size=batch_size)]
            out = {"image": self.images[idx]}
            if self.labels is not None:
                out["label"] = self.labels[idx]
            yield out


_SCAN_CHUNK = 64 * 1024 * 1024
# Files whose checksums verified on a complete pass — keyed by
# (path, mtime_ns, size) so an overwritten/regenerated file is
# re-verified instead of inheriting a stale verdict (ISSUE 15 satellite).
_CRC_VERIFIED: set = set()


def _file_sig(path: str) -> Tuple[int, int]:
    st = os.stat(path)
    return int(st.st_mtime_ns), int(st.st_size)


def _iter_tfrecord_raw(path: str) -> Iterator[bytes]:
    """Minimal TFRecord reader — no TF dependency on the hot path.

    Record framing (TFRecord spec): u64 length, u32 masked-crc(length),
    payload, u32 masked-crc(payload).

    Fast path: the native host-ops frame scanner
    (gansformer_tpu/native) over 64 MB chunks, WITH checksum
    verification — corruption raises instead of feeding garbage.
    Fallback: Python framing with CRCs skipped (the reference's reader
    delegates to tf.data which checks them; in pure Python the
    cost/benefit favors skipping).
    """
    from gansformer_tpu import native

    sig = (path, *_file_sig(path))
    if native.get_lib() is not None and sig not in _CRC_VERIFIED:
        # First pass over a file version: native chunked scan WITH
        # checksums, so a corrupt dataset fails loudly up front.  Later
        # passes over the SAME (mtime, size) use the lighter per-record
        # framing below (still native proto parse), which measures ~2×
        # faster in steady state.
        verify = True
        with open(path, "rb") as f:
            leftover = b""
            while True:
                chunk = f.read(_SCAN_CHUNK)
                buf = leftover + chunk
                if not buf:
                    _CRC_VERIFIED.add(sig)
                    return
                offs, lens, consumed = native.scan_records(
                    buf, verify_crc=verify)
                for o, ln in zip(offs, lens):
                    yield buf[int(o):int(o) + int(ln)]
                leftover = buf[consumed:]
                if not chunk:          # EOF
                    if leftover:
                        raise ValueError(
                            f"truncated TFRecord at end of {path} "
                            f"({len(leftover)} trailing bytes)")
                    _CRC_VERIFIED.add(sig)
                    return
                if consumed == 0 and len(buf) > 2**30:
                    # bounds RAM if a corrupt length field claims a
                    # multi-GB record (largest real record ≈ 3 MB at 1024²)
                    raise ValueError(
                        f"TFRecord record larger than 1 GiB in {path} — "
                        f"corrupt length field?")
        return

    with open(path, "rb") as f:
        while True:
            head = f.read(12)
            if len(head) < 12:
                return
            (length,) = struct.unpack("<Q", head[:8])
            payload = f.read(length)
            f.read(4)  # payload crc
            yield payload


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _walk_proto(buf: bytes):
    """Yield (field_number, wire_type, value) for one protobuf message.
    value is bytes for length-delimited fields, int for varint."""
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:        # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 2:      # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:      # fixed32
            val = struct.unpack("<I", buf[pos:pos + 4])[0]
            pos += 4
        elif wt == 1:      # fixed64
            val = struct.unpack("<Q", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _parse_example_image(payload: bytes) -> np.ndarray:
    """Parse of the reference's ``tf.train.Example`` {shape: int64[3],
    data: bytes} — no TensorFlow dependency.

    Fast path: the native host-ops lib (gansformer_tpu/native, C++ proto
    walk returning spans; images come out as zero-copy ``np.frombuffer``
    views).  Fallback: the hand-rolled Python walk below.

    Proto schema (tensorflow/core/example/example.proto):
      Example.features(1) → Features.feature(1) map<string, Feature> →
      MapEntry{key(1), value(2)} → Feature{bytes_list(1)|int64_list(3)} →
      {BytesList,Int64List}.value(1).
    Raises on malformed records; the TFRecord source catches the raise
    and QUARANTINES the record (budgeted — docs/data.md) instead of
    killing the run on a static defect.
    """
    from gansformer_tpu import native

    parsed = native.parse_example(payload) if native.get_lib() else None
    if parsed is not None:
        shape, d_off, d_len = parsed
        arr = np.frombuffer(payload, np.uint8, count=d_len,
                            offset=d_off).reshape(shape)
        if arr.ndim == 3 and arr.shape[0] in (1, 3) and \
                arr.shape[0] < arr.shape[2]:
            arr = arr.transpose(1, 2, 0)  # CHW (reference layout) → HWC
        return arr

    features = None
    for field, _, val in _walk_proto(payload):
        if field == 1:                      # Example.features
            features = val
    if features is None:
        raise ValueError("record has no Features message")

    shape = None
    data = None
    for field, _, entry in _walk_proto(features):
        if field != 1:                      # Features.feature map entries
            continue
        key = None
        feat = None
        for f2, _, v2 in _walk_proto(entry):
            if f2 == 1:
                key = v2.decode()
            elif f2 == 2:
                feat = v2
        if key == "shape" and feat is not None:
            for f3, _, v3 in _walk_proto(feat):
                if f3 == 3:                 # Feature.int64_list
                    vals = []
                    for f4, wt4, v4 in _walk_proto(v3):
                        if f4 == 1 and wt4 == 0:
                            vals.append(v4)
                        elif f4 == 1 and wt4 == 2:   # packed
                            p = 0
                            while p < len(v4):
                                x, p = _read_varint(v4, p)
                                vals.append(x)
                    shape = vals
        elif key == "data" and feat is not None:
            for f3, _, v3 in _walk_proto(feat):
                if f3 == 1:                 # Feature.bytes_list
                    for f4, _, v4 in _walk_proto(v3):
                        if f4 == 1:
                            data = v4
    if shape is None or data is None:
        raise ValueError("record missing 'shape' or 'data' feature")
    arr = np.frombuffer(data, np.uint8).reshape(shape)
    if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]:
        arr = arr.transpose(1, 2, 0)  # CHW (reference layout) → HWC
    return arr


# --- record-offset index (ISSUE 15 tentpole 1) -------------------------------

_INDEX_VERSION = 1


def _index_path(path: str) -> str:
    return path + ".idx.npz"


def _py_scan_frames(buf: bytes):
    """Python framing fallback: (payload offsets, lengths, consumed) for
    every COMPLETE record frame in ``buf`` — lengths trusted (no CRC),
    mirroring the pre-index Python read path."""
    offs: List[int] = []
    lens: List[int] = []
    pos = 0
    n = len(buf)
    while pos + 12 <= n:
        (length,) = struct.unpack("<Q", buf[pos:pos + 8])
        end = pos + 12 + length + 4
        if length > 2**30 or end > n:
            break                      # partial tail or hostile length
        offs.append(pos + 12)
        lens.append(length)
        pos = end
    return offs, lens, pos


def build_record_index(path: str) -> dict:
    """One streaming pass over a TFRecord file → the offset index:
    ``offsets``/``lengths`` (np.int64, absolute payload spans) of every
    record whose framing — and, with the native lib, payload CRC —
    verifies, plus ``bad`` [(offset, length, cause)] for records
    quarantined at scan time.  A tail whose framing cannot be walked
    (torn file, corrupt length field) becomes ONE ``unframeable-tail``
    entry covering the rest of the file — the scanner cannot resync
    past a broken frame, but everything before it stays readable."""
    from gansformer_tpu import native
    from gansformer_tpu.data.tfrecord_writer import _masked_crc

    lib = native.get_lib()
    size = os.path.getsize(path)
    offsets: List[int] = []
    lengths: List[int] = []
    bad: List[Tuple[int, int, str]] = []
    base = 0                       # file offset of buf[0]
    leftover = b""
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_SCAN_CHUNK)
            buf = leftover + chunk
            if not buf:
                break
            if lib is not None:
                offs, lens, consumed = native.scan_records(
                    buf, verify_crc=False)
            else:
                offs, lens, consumed = _py_scan_frames(buf)
            for o, ln in zip(offs, lens):
                o, ln = int(o), int(ln)
                if lib is not None:
                    (want,) = struct.unpack("<I", buf[o + ln:o + ln + 4])
                    if _masked_crc(buf[o:o + ln]) != want:
                        bad.append((base + o, ln, "payload-crc"))
                        continue
                offsets.append(base + o)
                lengths.append(ln)
            leftover = buf[consumed:]
            base += consumed
            if not chunk:              # EOF
                if leftover:
                    bad.append((base, len(leftover), "unframeable-tail"))
                break
            if consumed == 0 and len(buf) > 2**30:
                # a corrupt length field claims a multi-GB record: stop
                # scanning, quarantine the rest of the file as one span
                bad.append((base, size - base, "unframeable-tail"))
                break
    return {"offsets": np.asarray(offsets, np.int64),
            "lengths": np.asarray(lengths, np.int64),
            "bad": bad}


def load_record_index(path: str) -> dict:
    """The file's record-offset index — from the ``<file>.idx.npz``
    sidecar when it matches the file's (mtime_ns, size) signature, else
    rebuilt by one scan pass and persisted (best-effort: a read-only
    dataset dir keeps the index in memory for the process)."""
    mtime_ns, size = _file_sig(path)
    sidecar = _index_path(path)
    if os.path.exists(sidecar):
        try:
            with np.load(sidecar, allow_pickle=False) as z:
                if (int(z["version"]) == _INDEX_VERSION
                        and int(z["mtime_ns"]) == mtime_ns
                        and int(z["size"]) == size):
                    return {
                        "offsets": z["offsets"].astype(np.int64),
                        "lengths": z["lengths"].astype(np.int64),
                        "bad": [(int(o), int(ln), str(c)) for o, ln, c in
                                zip(z["bad_offsets"], z["bad_lengths"],
                                    z["bad_causes"])]}
        except (OSError, ValueError, KeyError):
            pass                       # torn/stale sidecar: rebuild
    idx = build_record_index(path)
    try:
        tmp = f"{sidecar}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(
                f, version=np.int64(_INDEX_VERSION),
                mtime_ns=np.int64(mtime_ns), size=np.int64(size),
                offsets=idx["offsets"], lengths=idx["lengths"],
                bad_offsets=np.asarray([b[0] for b in idx["bad"]], np.int64),
                bad_lengths=np.asarray([b[1] for b in idx["bad"]], np.int64),
                bad_causes=np.asarray([b[2] for b in idx["bad"]], np.str_))
        os.replace(tmp, sidecar)
    except OSError:
        pass                           # unwritable dataset dir: in-memory
    return idx


def _lod_of(fname: str) -> int:
    m = re.findall(r"-r(\d+)", os.path.basename(fname))
    return int(m[-1]) if m else -1


class TFRecordDataset(Dataset):
    """Index-addressed reader of the reference's multi-resolution TFRecord
    layout: ``<dir>/<name>-r{02..10}.tfrecords`` + optional ``*.labels``
    (SURVEY.md §3.4).  ALL files matching the selected resolution are
    read (a sharded dataset's shards are one logical source); each file
    carries a record-offset index sidecar (``<file>.idx.npz``) built on
    first pass, so every record is addressable by (file, offset, length):

    * ``batches(start_batch=N)`` fast-forwards by advancing the RNG
      stream only — kill→resume runs are tick-for-tick loss-identical
      (the ROADMAP item 5 resume-exact contract, tests/test_supervise).
    * Shuffling is per-epoch permutation of the shard-local good-record
      set (every record exactly once per epoch, like the reference's
      epoch-wide shuffle; ``shuffle_buffer`` is accepted for API compat
      but the index makes the decoded-window reservoir unnecessary).
    * Corrupt records (bad payload CRC at index build, malformed proto
      at decode) are QUARANTINED — offset+cause appended to the
      ``data_quarantine.jsonl`` ledger, ``data/corrupt_records_total``
      incremented, the batch slot deterministically re-filled from the
      next good record — and the run only fails typed (``DataCorrupt``)
      once quarantined/total exceeds ``max_corrupt_frac``.
    * Transient read errors retry under bounded exponential backoff
      (``io_retries`` × ``io_retry_base_s``, ``data/read_retries_total``).

    Fault points (supervise/faults.py): ``data_read_error`` /
    ``data_slow_read`` fire before every record read (coordinate ``n`` =
    monotonic read count), ``data_corrupt_record`` before every proto
    parse (coordinate ``n`` = monotonic parse count).
    """

    def __init__(self, path: str, resolution: Optional[int] = None,
                 shuffle_buffer: int = 4096,
                 max_corrupt_frac: float = 0.01,
                 io_retries: int = 3,
                 io_retry_base_s: float = 0.05):
        self.path = path
        files = sorted(glob.glob(os.path.join(path, "*.tfrecords")))
        if not files:
            raise FileNotFoundError(f"no .tfrecords under {path}")
        match = []
        if resolution is not None:
            lod = int(np.log2(resolution))
            match = [f for f in files
                     if f"-r{lod:02d}" in os.path.basename(f)]
        if not match:
            # No (or no matching) lod tag: fall back to the highest
            # single-resolution group — the pre-index reader's
            # files[-1] behavior, but never a MIX of lods, which the
            # shape check would read as mass corruption against the
            # probed resolution (spurious DataCorrupt).
            top = max(_lod_of(f) for f in files)
            match = [f for f in files if _lod_of(f) == top]
        files = match
        self.files = files
        self.file = files[-1]   # back-compat alias (pre-ISSUE-15 attr)
        self.shuffle_buffer = shuffle_buffer
        self.max_corrupt_frac = float(max_corrupt_frac)
        self.io_retries = int(io_retries)
        self.io_retry_base_s = float(io_retry_base_s)

        self._c_corrupt = telemetry.counter("data/corrupt_records_total")
        self._c_retries = telemetry.counter("data/read_retries_total")
        self._g_frac = telemetry.gauge("data/corrupt_frac")
        self._ledger_path: Optional[str] = None
        self._pending_ledger: List[dict] = []
        self._bad_seen: set = set()     # {(file_idx, offset)}
        self._fds: dict = {}
        self._reads = 0
        self._parses = 0

        # Per-file indexes → one flat addressable record table.  A good
        # record's ORIGINAL position (its rank among good+bad records in
        # file order) indexes the label array — labels stay aligned even
        # when earlier records are quarantined.
        rec_file: List[np.ndarray] = []
        rec_off: List[np.ndarray] = []
        rec_len: List[np.ndarray] = []
        rec_orig: List[np.ndarray] = []
        total_scanned = 0
        for fi, fn in enumerate(self.files):
            idx = load_record_index(fn)
            offs, lens, bad = idx["offsets"], idx["lengths"], idx["bad"]
            all_offs = np.sort(np.concatenate(
                [offs, np.asarray([b[0] for b in bad], np.int64)]))
            rec_file.append(np.full(len(offs), fi, np.int32))
            rec_off.append(offs)
            rec_len.append(lens)
            rec_orig.append(total_scanned
                            + np.searchsorted(all_offs, offs))
            total_scanned += len(offs) + len(bad)
            for off, ln, cause in bad:
                self._note_bad(fi, int(off), int(ln), cause, check=False)
        self._rec_file = np.concatenate(rec_file)
        self._rec_off = np.concatenate(rec_off)
        self._rec_len = np.concatenate(rec_len)
        self._rec_orig = np.concatenate(rec_orig)
        self._total_scanned = total_scanned
        self.num_images = len(self._rec_off)
        if self.num_images == 0:
            raise DataCorrupt(
                f"no readable records under {path} "
                f"({len(self._bad_seen)} quarantined)")
        self._check_budget()

        first = self._read_parse(0)[1]
        self.resolution = first.shape[0]
        self.channels = first.shape[2]

        label_files = glob.glob(os.path.join(path, "*.labels"))
        self.labels = None
        if label_files:
            self.labels = np.load(label_files[0]).astype(np.float32)
            if len(self.labels) != total_scanned:
                raise ValueError(
                    f"label file {label_files[0]} has {len(self.labels)} "
                    f"rows but the matched record set "
                    f"({len(self.files)} file(s)) holds {total_scanned} "
                    f"records — labels would silently mis-align; "
                    f"regenerate the labels beside the shards")
            self.has_labels = True
            self.label_dim = self.labels.shape[1]

    # -- quarantine ----------------------------------------------------------

    def set_quarantine_ledger(self, path: str) -> None:
        self._ledger_path = path
        pending, self._pending_ledger = self._pending_ledger, []
        for rec in pending:
            self._ledger_append(rec)

    def _ledger_append(self, rec: dict) -> None:
        if self._ledger_path is None:
            self._pending_ledger.append(rec)
            return
        import json

        with open(self._ledger_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def _note_bad(self, fi: int, off: int, ln: int, cause: str,
                  check: bool = True) -> None:
        key = (fi, off)
        if key in self._bad_seen:
            return
        self._bad_seen.add(key)
        self._c_corrupt.inc()
        self._ledger_append({
            "file": self.files[fi], "offset": off, "length": ln,
            "cause": cause, "time": time.time(), "pid": os.getpid()})
        if check:
            self._g_frac.set(len(self._bad_seen)
                             / max(self._total_scanned, 1))
            self._check_budget()

    def _check_budget(self) -> None:
        frac = len(self._bad_seen) / max(self._total_scanned, 1)
        self._g_frac.set(frac)
        if frac > self.max_corrupt_frac:
            raise DataCorrupt(
                f"{len(self._bad_seen)}/{self._total_scanned} records "
                f"quarantined ({frac:.1%}) exceeds max_corrupt_frac="
                f"{self.max_corrupt_frac:g} under {self.path} — a static "
                f"data defect; see the data_quarantine.jsonl ledger "
                f"(restarting cannot fix this)")

    # -- record IO -----------------------------------------------------------

    def close(self) -> None:
        """Close every cached record fd (idempotent — raw fds are not
        reclaimed by GC, so a process churning dataset instances would
        otherwise leak one per shard per instance)."""
        fds, self._fds = self._fds, {}
        for fd in fds.values():
            try:
                os.close(fd)
            except OSError:
                pass

    def _fd(self, fi: int) -> int:
        fd = self._fds.get(fi)
        if fd is None:
            fd = os.open(self.files[fi], os.O_RDONLY)
            self._fds[fi] = fd
        return fd

    def _read_record(self, pos: int) -> bytes:
        """Payload bytes of good-record ``pos``, retrying transient IO
        errors under bounded exponential backoff (``os.pread`` — no seek
        state, safe across generator instances)."""
        fi = int(self._rec_file[pos])
        off = int(self._rec_off[pos])
        ln = int(self._rec_len[pos])
        attempt = 0
        while True:
            self._reads += 1
            try:
                faults.fire("data_slow_read", n=self._reads)
                faults.fire("data_read_error", n=self._reads)
                data = os.pread(self._fd(fi), ln, off)
                if len(data) != ln:
                    # truncated-since-index: corruption, not a transient
                    raise ValueError(
                        f"short read ({len(data)}/{ln} bytes) at "
                        f"{self.files[fi]}:{off}")
                return data
            except (OSError, faults.FaultInjected) as e:
                old = self._fds.pop(fi, None)
                if old is not None:
                    try:
                        os.close(old)
                    except OSError:
                        pass
                attempt += 1
                if attempt > self.io_retries:
                    raise OSError(
                        f"read of {self.files[fi]}@{off} failed after "
                        f"{attempt} attempt(s): {e}") from e
                self._c_retries.inc()
                time.sleep(self.io_retry_base_s * (2 ** (attempt - 1)))

    def _read_parse(self, pos: int) -> Tuple[int, np.ndarray]:
        """Decode good-record ``pos`` — on a corrupt record, quarantine
        it and deterministically substitute the next good record (the
        same corrupt bytes map to the same substitute on every run, so
        the stream stays resume-exact on a static defect)."""
        for probe in range(self.num_images):
            p = (pos + probe) % self.num_images
            fi = int(self._rec_file[p])
            try:
                payload = self._read_record(p)
                self._parses += 1
                faults.fire("data_corrupt_record", n=self._parses)
                arr = _parse_example_image(payload)
                if getattr(self, "resolution", None) and arr.shape != (
                        self.resolution, self.resolution, self.channels):
                    raise ValueError(f"record shape {arr.shape} != dataset "
                                     f"{(self.resolution, self.resolution, self.channels)}")
                return p, arr
            except (ValueError, IndexError, UnicodeDecodeError,
                    faults.FaultInjected) as e:
                self._note_bad(fi, int(self._rec_off[p]),
                               int(self._rec_len[p]),
                               f"{type(e).__name__}: {str(e)[:200]}")
        raise DataCorrupt(f"no readable record left under {self.path}")

    # -- stream --------------------------------------------------------------

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        local = np.arange(shard_id, self.num_images, num_shards)
        n = len(local)
        if n < batch_size:
            raise ValueError(
                f"shard {shard_id}/{num_shards} holds {n} record(s) < "
                f"batch_size {batch_size}")
        per_epoch = n // batch_size
        # Seekable fast-forward: whole epochs advance the permutation
        # stream only (one rs.permutation call each — no decode), which
        # is what makes kill→resume tick-parity exact on TFRecords.
        epochs, r = divmod(start_batch, per_epoch)
        for _ in range(epochs):
            rs.permutation(n)
        perm = rs.permutation(n)
        pos = r * batch_size
        while True:
            if pos + batch_size > per_epoch * batch_size:
                perm = rs.permutation(n)
                pos = 0
            idx = local[perm[pos:pos + batch_size]]
            pos += batch_size
            yield self._emit(idx)

    def _emit(self, idx: Sequence[int]) -> dict:
        imgs = []
        orig = []
        for i in idx:
            p, arr = self._read_parse(int(i))
            imgs.append(arr)
            orig.append(int(self._rec_orig[p]))
        out = {"image": np.stack(imgs)}
        if self.labels is not None:
            out["label"] = self.labels[np.asarray(orig)]
        return out


class ImageFolderDataset(Dataset):
    """Directory of PNG/JPG images, centre-cropped + resized to a power-of-2
    resolution (the role of the reference's ``dataset_tool.py
    create_from_images`` — but done on the fly)."""

    def __init__(self, path: str, resolution: int):
        self.path = path
        exts = (".png", ".jpg", ".jpeg", ".bmp", ".webp")
        self.files = sorted(
            os.path.join(r, fn)
            for r, _, fns in os.walk(path)
            for fn in fns if fn.lower().endswith(exts))
        if not self.files:
            raise FileNotFoundError(f"no images under {path}")
        self.resolution = resolution
        self.channels = 3
        self.num_images = len(self.files)

    def _load(self, fn: str) -> np.ndarray:
        from PIL import Image

        img = Image.open(fn).convert("RGB")
        s = min(img.size)
        left = (img.size[0] - s) // 2
        top = (img.size[1] - s) // 2
        img = img.crop((left, top, left + s, top + s))
        img = img.resize((self.resolution, self.resolution), Image.LANCZOS)
        return np.asarray(img, dtype=np.uint8)

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        local = np.arange(shard_id, len(self.files), num_shards)
        for _ in range(start_batch):   # advance the index stream only
            rs.randint(0, len(local), size=batch_size)
        while True:
            idx = local[rs.randint(0, len(local), size=batch_size)]
            yield {"image": np.stack([self._load(self.files[i]) for i in idx])}


class PrefetchIterator:
    """Background-thread prefetch with a bounded queue — overlaps host-side
    decode/shuffle with device compute (the tf.data ``prefetch`` analog the
    reference gets for free from its in-graph input pipeline).

    Exceptions raised by the producer surface on the consumer's next
    ``next()``; ``close()`` (also via context manager) stops the thread.

    Stall watchdog (ISSUE 15): with ``stall_after_s > 0``, a consumer
    blocked on an empty queue while the producer makes NO progress for
    that long raises typed ``DataStalled`` — a classified, fast data-hang
    signal (wedged NFS read, hung decode) instead of waiting for the
    supervisor's generic heartbeat-staleness SIGKILL.  Progress = items
    landing in the queue, so ``stall_after_s`` must exceed the worst-case
    single-batch decode time.

    Telemetry (obs/registry): ``data/prefetch_queue_depth`` gauge (ready
    batches waiting), ``data/starved_total`` counter (consumer arrived
    to an empty queue — the producer is the bottleneck), ``data/wait_ms``
    histogram (per-``next()`` block time), ``data/batches_total``,
    ``data/stalls_total`` (watchdog verdicts).
    """

    _SENTINEL = object()

    def __init__(self, iterator: Iterator[dict], depth: int = 2,
                 stall_after_s: float = 0.0):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._finished = False
        self._error: Optional[BaseException] = None
        self._stall_after_s = float(stall_after_s or 0.0)
        self._last_progress = time.monotonic()
        self._g_depth = telemetry.gauge("data/prefetch_queue_depth")
        self._c_starved = telemetry.counter("data/starved_total")
        self._c_batches = telemetry.counter("data/batches_total")
        self._c_stalls = telemetry.counter("data/stalls_total")
        self._h_wait_ms = telemetry.histogram("data/wait_ms")

        def _produce():
            try:
                for n, item in enumerate(iterator):
                    # Fault-injection point: a 'hang' armed here models
                    # the wedged data thread — with the watchdog armed
                    # the consumer raises DataStalled; without it the
                    # loop blocks in data_wait until the supervisor's
                    # staleness probe ends the run.
                    faults.fire("data_thread", batch=n)
                    while not self._stop.is_set():
                        try:
                            self._queue.put(item, timeout=0.1)
                            self._last_progress = time.monotonic()
                            self._g_depth.set(self._queue.qsize())
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 — reraised on consumer
                self._error = e
            finally:
                while not self._stop.is_set():
                    try:
                        self._queue.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=_produce, daemon=True)
        self._thread.start()

    def _pop(self):
        """Blocking pop under the shared stall-watchdog conviction rule
        (``errors.stall_guarded_get`` — one algorithm for both prefetch
        layers)."""
        return stall_guarded_get(
            self._queue, self._stall_after_s,
            lambda: self._last_progress, self._c_stalls,
            "data producer")

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        if self._finished or self._stop.is_set():
            raise StopIteration
        starved = self._queue.empty()
        t0 = time.perf_counter()
        item = self._pop()
        if item is self._SENTINEL:
            # end-of-stream teardown wait is not data starvation — don't
            # let it skew the input-bound diagnosis counters
            self._finished = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        if starved:                  # device-side starvation: input-bound
            self._c_starved.inc()
        self._h_wait_ms.observe((time.perf_counter() - t0) * 1000.0)
        self._g_depth.set(self._queue.qsize())
        self._c_batches.inc()
        return item

    def close(self) -> None:
        """Stop and join the producer thread.  Idempotent — the loop's
        ``finally`` and a context-manager exit may both call it.  After
        the join a sentinel is parked in the queue so any *consumer*
        blocked in ``__next__`` (e.g. a ``DevicePrefetcher`` transfer
        thread pulling from this iterator) wakes with StopIteration
        instead of hanging on a drained queue."""
        self._stop.set()
        try:  # unblock a producer stuck on a full queue
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        try:   # wake consumers blocked on the (now idle) queue
            self._queue.put_nowait(self._SENTINEL)
        except queue.Full:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_dataset(cfg) -> Dataset:
    """cfg: DataConfig (core.config)."""
    if cfg.source == "synthetic":
        return SyntheticDataset(resolution=cfg.resolution, channels=cfg.channels)
    if cfg.source == "npz":
        return NpzDataset(cfg.path)
    if cfg.source == "tfrecord":
        return TFRecordDataset(cfg.path, resolution=cfg.resolution,
                               shuffle_buffer=cfg.shuffle_buffer,
                               max_corrupt_frac=cfg.max_corrupt_frac,
                               io_retries=cfg.io_retries,
                               io_retry_base_s=cfg.io_retry_base_s)
    if cfg.source == "folder":
        return ImageFolderDataset(cfg.path, resolution=cfg.resolution)
    raise ValueError(f"unknown data source {cfg.source!r}")


def normalize_images(uint8_images) -> "jax.Array":  # noqa: F821
    """uint8 [N,H,W,C] → float32 in [-1, 1] (done on device)."""
    import jax.numpy as jnp

    return uint8_images.astype(jnp.float32) / 127.5 - 1.0
