"""Input pipeline — host-side dataset readers feeding the device mesh.

Capability parity with the reference's ``src/training/dataset.py``
(``TFRecordDataset``: multi-resolution TFRecords, shuffle/prefetch, optional
labels; SURVEY.md §2.2/§3.4).  Re-designed for the JAX/TPU input model:

* The reference builds a ``tf.data`` graph wired *into* the TF1 training
  graph.  Under JAX the input pipeline is host-side Python/numpy producing
  per-process batch shards that the train loop ``device_put``\\ s onto the
  ``data`` mesh axis (SURVEY.md §7.3 item 6: per-host shard of records, no
  cross-host shuffle).
* Images flow as NHWC uint8 on the host and are normalized to [-1, 1] float
  on device (saves 4x host→device bandwidth vs shipping f32 — HBM/PCIe
  friendly).
* ``TFRecordDataset`` reads the reference's record format
  (``<name>-r{lod}.tfrecords``, features: shape [3] int64 + data bytes,
  CHW uint8) so datasets prepared for the reference work unchanged — via a
  hand-rolled TFRecord framing + protobuf walk, so the framework has NO
  TensorFlow dependency.  Malformed records raise (loud corruption beats a
  silently shrinking dataset).
"""

from __future__ import annotations

import glob
import os
import queue
import struct
import threading
import time
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from gansformer_tpu.obs import registry as telemetry


class Dataset:
    """Iterator protocol: ``batches(batch_size)`` yields dicts with
    ``image`` [N,H,W,C] uint8 and optional ``label`` [N,label_dim] f32."""

    resolution: int
    channels: int
    has_labels: bool = False
    label_dim: int = 0
    num_images: Optional[int] = None

    def batches(self, batch_size: int, seed: int = 0,
                shard: Tuple[int, int] = (0, 1),
                start_batch: int = 0) -> Iterator[dict]:
        """Infinite batch stream.  ``start_batch`` positions the stream
        at batch index N of the seed-determined sequence — the resume
        contract: a run restored at iteration N consumes the same
        batches an uninterrupted run would, so loss trajectories stay
        tick-for-tick comparable across restarts.  Index-addressed
        sources fast-forward by advancing the RNG stream only (no image
        decode); sequential sources (TFRecord) document best-effort."""
        raise NotImplementedError

    def random_labels(self, n: int, seed: int = 0) -> Optional[np.ndarray]:
        """n labels drawn from the dataset's label distribution (reference
        ``get_random_labels``) — for conditional sampling at eval/snapshot
        time.  None for unconditional datasets."""
        labels = getattr(self, "labels", None)
        if labels is None:
            return None
        rs = np.random.RandomState(seed)
        return labels[rs.randint(0, len(labels), size=n)]

    def cache_tag(self) -> str:
        """Stable identity for disk caches (e.g. FID real-stats) — must
        distinguish different datasets, not just different classes."""
        src = getattr(self, "path", None) or getattr(self, "file", None) or ""
        return f"{self.__class__.__name__}-{src}-{self.resolution}"


class SyntheticDataset(Dataset):
    """Deterministic procedural images for smoke tests and CI.

    Replaces nothing in the reference (it has no test data story — SURVEY.md
    §4); exists so the end-to-end slice runs with zero downloads.  Produces
    smooth multi-scale Gabor-ish blobs with enough structure that D can
    learn *something* and FID-on-synthetic is a meaningful pipeline test.
    """

    def __init__(self, resolution: int = 64, channels: int = 3,
                 num_images: int = 10000):
        self.resolution = resolution
        self.channels = channels
        self.num_images = num_images

    def _make(self, idx: np.ndarray) -> np.ndarray:
        r, c = self.resolution, self.channels
        yy, xx = np.mgrid[0:r, 0:r].astype(np.float32) / r  # [r,r]
        imgs = np.empty((len(idx), r, r, c), dtype=np.uint8)
        for i, seed in enumerate(idx):
            rs = np.random.RandomState(int(seed) % (2**31))
            img = np.zeros((r, r, c), np.float32)
            for _ in range(4):
                fx, fy = rs.uniform(1, 6, 2)
                px, py = rs.uniform(0, 2 * np.pi, 2)
                cx, cy = rs.uniform(0.2, 0.8, 2)
                sig = rs.uniform(0.1, 0.4)
                blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * sig**2)))
                wave = np.sin(2 * np.pi * (fx * xx + px)) * np.sin(
                    2 * np.pi * (fy * yy + py))
                col = rs.uniform(-1, 1, c).astype(np.float32)
                img += (blob * wave)[..., None] * col
            img = np.tanh(img)
            imgs[i] = ((img * 0.5 + 0.5) * 255).astype(np.uint8)
        return imgs

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        for _ in range(start_batch):   # advance the index stream only
            rs.randint(0, self.num_images, size=batch_size)
        while True:
            idx = rs.randint(0, self.num_images, size=batch_size)
            idx = idx * num_shards + shard_id  # disjoint streams per host
            yield {"image": self._make(idx)}


class NpzDataset(Dataset):
    """Packed numpy archive: ``images`` [N,H,W,C] uint8 (+ optional
    ``labels``).  The fast path for small datasets (CIFAR/CLEVR-scale)."""

    def __init__(self, path: str):
        self.path = path
        with np.load(path) as z:
            self.images = z["images"]
            self.labels = z["labels"].astype(np.float32) if "labels" in z else None
        assert self.images.dtype == np.uint8 and self.images.ndim == 4
        self.resolution = self.images.shape[1]
        self.channels = self.images.shape[3]
        self.num_images = len(self.images)
        self.has_labels = self.labels is not None
        self.label_dim = 0 if self.labels is None else self.labels.shape[1]

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        local = np.arange(shard_id, self.num_images, num_shards)
        for _ in range(start_batch):   # advance the index stream only
            rs.randint(0, len(local), size=batch_size)
        while True:
            idx = local[rs.randint(0, len(local), size=batch_size)]
            out = {"image": self.images[idx]}
            if self.labels is not None:
                out["label"] = self.labels[idx]
            yield out


_SCAN_CHUNK = 64 * 1024 * 1024
# Files whose checksums verified on a complete pass — corruption is a
# static property, so epochs 2+ skip the CRC work (~90 ms/GB).
_CRC_VERIFIED: set = set()


def _iter_tfrecord_raw(path: str) -> Iterator[bytes]:
    """Minimal TFRecord reader — no TF dependency on the hot path.

    Record framing (TFRecord spec): u64 length, u32 masked-crc(length),
    payload, u32 masked-crc(payload).

    Fast path: the native host-ops frame scanner
    (gansformer_tpu/native) over 64 MB chunks, WITH checksum
    verification — corruption raises instead of feeding garbage.
    Fallback: Python framing with CRCs skipped (the reference's reader
    delegates to tf.data which checks them; in pure Python the
    cost/benefit favors skipping).
    """
    from gansformer_tpu import native

    if native.get_lib() is not None and path not in _CRC_VERIFIED:
        # First pass over a file: native chunked scan WITH checksums, so a
        # corrupt dataset fails loudly up front.  Later passes use the
        # lighter per-record framing below (still native proto parse),
        # which measures ~2× faster in steady state.
        verify = True
        with open(path, "rb") as f:
            leftover = b""
            while True:
                chunk = f.read(_SCAN_CHUNK)
                buf = leftover + chunk
                if not buf:
                    _CRC_VERIFIED.add(path)
                    return
                offs, lens, consumed = native.scan_records(
                    buf, verify_crc=verify)
                for o, ln in zip(offs, lens):
                    yield buf[int(o):int(o) + int(ln)]
                leftover = buf[consumed:]
                if not chunk:          # EOF
                    if leftover:
                        raise ValueError(
                            f"truncated TFRecord at end of {path} "
                            f"({len(leftover)} trailing bytes)")
                    _CRC_VERIFIED.add(path)
                    return
                if consumed == 0 and len(buf) > 2**30:
                    # bounds RAM if a corrupt length field claims a
                    # multi-GB record (largest real record ≈ 3 MB at 1024²)
                    raise ValueError(
                        f"TFRecord record larger than 1 GiB in {path} — "
                        f"corrupt length field?")
        return

    with open(path, "rb") as f:
        while True:
            head = f.read(12)
            if len(head) < 12:
                return
            (length,) = struct.unpack("<Q", head[:8])
            payload = f.read(length)
            f.read(4)  # payload crc
            yield payload


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _walk_proto(buf: bytes):
    """Yield (field_number, wire_type, value) for one protobuf message.
    value is bytes for length-delimited fields, int for varint."""
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:        # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 2:      # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:      # fixed32
            val = struct.unpack("<I", buf[pos:pos + 4])[0]
            pos += 4
        elif wt == 1:      # fixed64
            val = struct.unpack("<Q", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _parse_example_image(payload: bytes) -> np.ndarray:
    """Parse of the reference's ``tf.train.Example`` {shape: int64[3],
    data: bytes} — no TensorFlow dependency.

    Fast path: the native host-ops lib (gansformer_tpu/native, C++ proto
    walk returning spans; images come out as zero-copy ``np.frombuffer``
    views).  Fallback: the hand-rolled Python walk below.

    Proto schema (tensorflow/core/example/example.proto):
      Example.features(1) → Features.feature(1) map<string, Feature> →
      MapEntry{key(1), value(2)} → Feature{bytes_list(1)|int64_list(3)} →
      {BytesList,Int64List}.value(1).
    Raises on malformed records (corruption must be loud, not a silent
    dataset shrink).
    """
    from gansformer_tpu import native

    parsed = native.parse_example(payload) if native.get_lib() else None
    if parsed is not None:
        shape, d_off, d_len = parsed
        arr = np.frombuffer(payload, np.uint8, count=d_len,
                            offset=d_off).reshape(shape)
        if arr.ndim == 3 and arr.shape[0] in (1, 3) and \
                arr.shape[0] < arr.shape[2]:
            arr = arr.transpose(1, 2, 0)  # CHW (reference layout) → HWC
        return arr

    features = None
    for field, _, val in _walk_proto(payload):
        if field == 1:                      # Example.features
            features = val
    if features is None:
        raise ValueError("record has no Features message")

    shape = None
    data = None
    for field, _, entry in _walk_proto(features):
        if field != 1:                      # Features.feature map entries
            continue
        key = None
        feat = None
        for f2, _, v2 in _walk_proto(entry):
            if f2 == 1:
                key = v2.decode()
            elif f2 == 2:
                feat = v2
        if key == "shape" and feat is not None:
            for f3, _, v3 in _walk_proto(feat):
                if f3 == 3:                 # Feature.int64_list
                    vals = []
                    for f4, wt4, v4 in _walk_proto(v3):
                        if f4 == 1 and wt4 == 0:
                            vals.append(v4)
                        elif f4 == 1 and wt4 == 2:   # packed
                            p = 0
                            while p < len(v4):
                                x, p = _read_varint(v4, p)
                                vals.append(x)
                    shape = vals
        elif key == "data" and feat is not None:
            for f3, _, v3 in _walk_proto(feat):
                if f3 == 1:                 # Feature.bytes_list
                    for f4, _, v4 in _walk_proto(v3):
                        if f4 == 1:
                            data = v4
    if shape is None or data is None:
        raise ValueError("record missing 'shape' or 'data' feature")
    arr = np.frombuffer(data, np.uint8).reshape(shape)
    if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]:
        arr = arr.transpose(1, 2, 0)  # CHW (reference layout) → HWC
    return arr


class TFRecordDataset(Dataset):
    """Reads the reference's multi-resolution TFRecord layout:
    ``<dir>/<name>-r{02..10}.tfrecords`` + optional ``<name>-rxx.labels``
    (SURVEY.md §3.4).  Only the max-resolution file is read (progressive
    growing is not part of the GANsformer configs)."""

    def __init__(self, path: str, resolution: Optional[int] = None,
                 shuffle_buffer: int = 4096):
        files = sorted(glob.glob(os.path.join(path, "*.tfrecords")))
        if not files:
            raise FileNotFoundError(f"no .tfrecords under {path}")
        if resolution is not None:
            lod = int(np.log2(resolution))
            match = [f for f in files if f"-r{lod:02d}" in f]
            files = match or files
        self.file = files[-1]  # highest resolution
        self.shuffle_buffer = shuffle_buffer
        first = _parse_example_image(next(_iter_tfrecord_raw(self.file)))
        self.resolution = first.shape[0]
        self.channels = first.shape[2]
        label_files = glob.glob(os.path.join(path, "*.labels"))
        self.labels = None
        if label_files:
            self.labels = np.load(label_files[0]).astype(np.float32)
            self.has_labels = True
            self.label_dim = self.labels.shape[1]

    # Byte budget for the decoded shuffle window: `shuffle_buffer` counts
    # images, so cap it by bytes too or a 1024² dataset would hold ~12.9 GB
    # per host at the 4096-image default.
    SHUFFLE_BYTES_BUDGET = 512 * 1024 * 1024

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        # start_batch is accepted but NOT seekable here: the stream is a
        # sequential file scan through a shuffle window, so a resumed
        # run re-reads from the file head (best-effort resume — the
        # strict tick-parity contract holds for index-addressed sources:
        # synthetic/npz/folder).
        del start_batch
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        # Reservoir-style shuffle window (the tf.data shuffle_buffer analog):
        # fill to `shuffle_buffer` decoded images, shuffle, drain half, refill.
        img_bytes = self.resolution * self.resolution * self.channels
        byte_cap = max(1, self.SHUFFLE_BYTES_BUDGET // img_bytes)
        cap = max(min(self.shuffle_buffer, byte_cap), batch_size * 2)
        buf: list = []
        while True:
            for i, payload in enumerate(_iter_tfrecord_raw(self.file)):
                if i % num_shards != shard_id:
                    continue  # per-host shard, no cross-host shuffle (§7.3.6)
                buf.append((i, _parse_example_image(payload)))
                if len(buf) >= cap:
                    rs.shuffle(buf)
                    while len(buf) > cap // 2 and len(buf) >= batch_size:
                        take = [buf.pop() for _ in range(batch_size)]
                        yield self._emit(take)
            rs.shuffle(buf)  # epoch boundary: flush what's left
            while len(buf) >= batch_size:
                take = [buf.pop() for _ in range(batch_size)]
                yield self._emit(take)

    def _emit(self, items: Sequence[Tuple[int, np.ndarray]]) -> dict:
        idx = np.array([i for i, _ in items])
        out = {"image": np.stack([im for _, im in items])}
        if self.labels is not None:
            out["label"] = self.labels[idx % len(self.labels)]
        return out


class ImageFolderDataset(Dataset):
    """Directory of PNG/JPG images, centre-cropped + resized to a power-of-2
    resolution (the role of the reference's ``dataset_tool.py
    create_from_images`` — but done on the fly)."""

    def __init__(self, path: str, resolution: int):
        self.path = path
        exts = (".png", ".jpg", ".jpeg", ".bmp", ".webp")
        self.files = sorted(
            os.path.join(r, fn)
            for r, _, fns in os.walk(path)
            for fn in fns if fn.lower().endswith(exts))
        if not self.files:
            raise FileNotFoundError(f"no images under {path}")
        self.resolution = resolution
        self.channels = 3
        self.num_images = len(self.files)

    def _load(self, fn: str) -> np.ndarray:
        from PIL import Image

        img = Image.open(fn).convert("RGB")
        s = min(img.size)
        left = (img.size[0] - s) // 2
        top = (img.size[1] - s) // 2
        img = img.crop((left, top, left + s, top + s))
        img = img.resize((self.resolution, self.resolution), Image.LANCZOS)
        return np.asarray(img, dtype=np.uint8)

    def batches(self, batch_size, seed=0, shard=(0, 1), start_batch=0):
        rs = np.random.RandomState(seed)
        shard_id, num_shards = shard
        local = np.arange(shard_id, len(self.files), num_shards)
        for _ in range(start_batch):   # advance the index stream only
            rs.randint(0, len(local), size=batch_size)
        while True:
            idx = local[rs.randint(0, len(local), size=batch_size)]
            yield {"image": np.stack([self._load(self.files[i]) for i in idx])}


class PrefetchIterator:
    """Background-thread prefetch with a bounded queue — overlaps host-side
    decode/shuffle with device compute (the tf.data ``prefetch`` analog the
    reference gets for free from its in-graph input pipeline).

    Exceptions raised by the producer surface on the consumer's next
    ``next()``; ``close()`` (also via context manager) stops the thread.

    Telemetry (obs/registry): ``data/prefetch_queue_depth`` gauge (ready
    batches waiting), ``data/starved_total`` counter (consumer arrived
    to an empty queue — the producer is the bottleneck), ``data/wait_ms``
    histogram (per-``next()`` block time), ``data/batches_total``.
    """

    _SENTINEL = object()

    def __init__(self, iterator: Iterator[dict], depth: int = 2):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._finished = False
        self._error: Optional[BaseException] = None
        self._g_depth = telemetry.gauge("data/prefetch_queue_depth")
        self._c_starved = telemetry.counter("data/starved_total")
        self._c_batches = telemetry.counter("data/batches_total")
        self._h_wait_ms = telemetry.histogram("data/wait_ms")

        def _produce():
            from gansformer_tpu.supervise import faults

            try:
                for n, item in enumerate(iterator):
                    # Fault-injection point: a 'hang' armed here models
                    # the wedged data thread — the loop blocks in
                    # data_wait, heartbeats go stale, and only the
                    # supervisor's staleness probe ends the run.
                    faults.fire("data_thread", batch=n)
                    while not self._stop.is_set():
                        try:
                            self._queue.put(item, timeout=0.1)
                            self._g_depth.set(self._queue.qsize())
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 — reraised on consumer
                self._error = e
            finally:
                while not self._stop.is_set():
                    try:
                        self._queue.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=_produce, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        if self._finished or self._stop.is_set():
            raise StopIteration
        starved = self._queue.empty()
        t0 = time.perf_counter()
        item = self._queue.get()
        if item is self._SENTINEL:
            # end-of-stream teardown wait is not data starvation — don't
            # let it skew the input-bound diagnosis counters
            self._finished = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        if starved:                  # device-side starvation: input-bound
            self._c_starved.inc()
        self._h_wait_ms.observe((time.perf_counter() - t0) * 1000.0)
        self._g_depth.set(self._queue.qsize())
        self._c_batches.inc()
        return item

    def close(self) -> None:
        """Stop and join the producer thread.  Idempotent — the loop's
        ``finally`` and a context-manager exit may both call it.  After
        the join a sentinel is parked in the queue so any *consumer*
        blocked in ``__next__`` (e.g. a ``DevicePrefetcher`` transfer
        thread pulling from this iterator) wakes with StopIteration
        instead of hanging on a drained queue."""
        self._stop.set()
        try:  # unblock a producer stuck on a full queue
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        try:   # wake consumers blocked on the (now idle) queue
            self._queue.put_nowait(self._SENTINEL)
        except queue.Full:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_dataset(cfg) -> Dataset:
    """cfg: DataConfig (core.config)."""
    if cfg.source == "synthetic":
        return SyntheticDataset(resolution=cfg.resolution, channels=cfg.channels)
    if cfg.source == "npz":
        return NpzDataset(cfg.path)
    if cfg.source == "tfrecord":
        return TFRecordDataset(cfg.path, resolution=cfg.resolution,
                               shuffle_buffer=cfg.shuffle_buffer)
    if cfg.source == "folder":
        return ImageFolderDataset(cfg.path, resolution=cfg.resolution)
    raise ValueError(f"unknown data source {cfg.source!r}")


def normalize_images(uint8_images) -> "jax.Array":  # noqa: F821
    """uint8 [N,H,W,C] → float32 in [-1, 1] (done on device)."""
    import jax.numpy as jnp

    return uint8_images.astype(jnp.float32) / 127.5 - 1.0
