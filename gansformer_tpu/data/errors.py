"""Typed data-plane failures (ISSUE 15).

The fault-tolerant streaming plane distinguishes two terminal verdicts
from the generic crash:

* ``DataCorrupt`` — the corruption *budget* is exhausted: more than
  ``DataConfig.max_corrupt_frac`` of the dataset's records are
  quarantined.  This is a STATIC defect of the data on disk — restarting
  cannot fix it, so the train CLI converts it into the distinct
  ``events.EXIT_DATA_CORRUPT`` exit code and the supervisor classifies
  the exit as non-retryable (``data-corrupt``) instead of burning its
  restart budget on a crash loop.
* ``DataStalled`` — the input pipeline's producer made no progress for
  ``DataConfig.stall_after_s`` while the consumer waited.  A classified,
  fast data-hang signal (wedged NFS mount, hung decode thread) that
  reaches the loop long before the supervisor's generic
  heartbeat-staleness probe would SIGKILL the whole run.  Possibly
  transient, so its exit code (``events.EXIT_DATA_STALLED``) stays
  retryable — but the cause lands classified in the availability ledger.

Kept dependency-free (stdlib only) so the jax-free supervisor-side
readers can name them in messages without importing the data plane.
Also home to ``stall_guarded_get`` — the ONE conviction algorithm both
prefetch layers (``PrefetchIterator``, ``DevicePrefetcher``) wrap their
queue pops in, so the stall rule cannot drift between them.
"""

from __future__ import annotations

import queue
import time
from typing import Callable


class DataError(RuntimeError):
    """Base of the typed data-plane failures."""


class DataCorrupt(DataError):
    """Corruption budget exhausted — a static, non-retryable data defect."""


class DataStalled(DataError):
    """The data producer made no progress within the stall budget."""


def stall_guarded_get(q: "queue.Queue", stall_after_s: float,
                      last_progress: Callable[[], float],
                      stall_counter, what: str):
    """``q.get()`` bounded by the producer-progress stall watchdog.

    With ``stall_after_s <= 0`` this is a plain blocking get.  Otherwise
    the wait is sliced, and a producer that makes NO progress (as
    reported by the zero-arg ``last_progress`` monotonic-timestamp
    callable) past the budget is convicted with typed ``DataStalled``
    (after ``stall_counter.inc()``).  The clock measures from the LATER
    of producer progress and entry to this wait, so a producer that was
    merely blocked on a full queue is never convicted for the idle time.
    """
    if stall_after_s <= 0:
        return q.get()
    entered = time.monotonic()
    while True:
        try:
            return q.get(timeout=min(1.0, stall_after_s / 4))
        except queue.Empty:
            now = time.monotonic()
            ref = max(last_progress(), entered)
            if now - ref > stall_after_s:
                stall_counter.inc()
                raise DataStalled(
                    f"{what} made no progress for {now - ref:.0f}s "
                    f"(stall_after_s={stall_after_s:g})") from None
