"""Dataset download/cache — the role of the reference's
``prepare_data.py`` downloads + ``dnnlib.util.open_url`` cache
(SURVEY.md §2.2 "Dataset build/download CLI", §3.4; the requests/Pillow pins
at /root/reference/src/Dockerfile:10-11 exist for exactly this path).

Stdlib-only (urllib): streaming download to a ``.part`` file with Range
resume, sha256 verification, then atomic rename — a partial or corrupt
download can never be mistaken for a finished one.  The benchmark-dataset
registry records a direct URL where one exists and honest manual
instructions where the license forbids automation (the reference cannot
automate Cityscapes either — it requires a login).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import urllib.error
import urllib.request
import zipfile
from dataclasses import dataclass
from typing import Callable, Dict, Optional


@dataclass(frozen=True)
class DatasetSource:
    """One downloadable benchmark dataset (BASELINE.json:7-11 configs)."""

    name: str
    url: Optional[str]            # None → manual-download-only
    filename: str                 # archive name under the cache dir
    sha256: Optional[str] = None  # verified when known
    manual: Optional[str] = None  # instructions when url is None
    post: Optional[str] = None    # 'cifar10' | 'images' | 'lmdb' — how
                                  # prepare_data consumes the extracted tree


DATASETS: Dict[str, DatasetSource] = {
    "cifar10": DatasetSource(
        name="cifar10",
        url="https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
        filename="cifar-10-python.tar.gz",
        sha256="6d958be074577803d12ecdefd02955f39262c83c16fe9348329d7fe0b5c001ce",
        post="cifar10"),
    "clevr": DatasetSource(
        name="clevr",
        url="https://dl.fbaipublicfiles.com/clevr/CLEVR_v1.0.zip",
        filename="CLEVR_v1.0.zip",
        post="images"),
    "lsun-bedroom": DatasetSource(
        name="lsun-bedroom",
        url="http://dl.yf.io/lsun/scenes/bedroom_train_lmdb.zip",
        filename="bedroom_train_lmdb.zip",
        post="lmdb"),
    "ffhq": DatasetSource(
        name="ffhq", url=None, filename="",
        manual="FFHQ ships via Google Drive quota-gated links; download "
               "images1024x1024 from github.com/NVlabs/ffhq-dataset and "
               "point --source-dir at the folder."),
    "cityscapes": DatasetSource(
        name="cityscapes", url=None, filename="",
        manual="Cityscapes requires a registered login "
               "(cityscapes-dataset.com); download leftImg8bit_trainvaltest "
               "and point --source-dir at the folder."),
}


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def download(url: str, dest: str, sha256: Optional[str] = None,
             chunk: int = 1 << 20,
             progress: Optional[Callable[[int, Optional[int]], None]] = None,
             timeout: float = 60.0) -> str:
    """Stream ``url`` → ``dest`` with resume + integrity.

    Partial data lives in ``dest + '.part'``; an interrupted download resumes
    with a Range request.  Only after the (optional) sha256 check passes is
    the file atomically renamed to ``dest`` — readers can trust any file
    that exists under its final name.
    """
    if os.path.exists(dest):
        if sha256 and sha256_file(dest) != sha256:
            raise IOError(f"{dest} exists but fails its sha256 check; "
                          f"delete it to re-download")
        return dest
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    part = dest + ".part"
    meta = part + ".meta"          # ETag/Last-Modified of the .part's origin
    offset = os.path.getsize(part) if os.path.exists(part) else 0
    req = urllib.request.Request(url)
    if offset:
        req.add_header("Range", f"bytes={offset}-")
        # Resume validation (ADVICE r3: entries without a registry sha256
        # must not blindly append to a possibly-changed origin file): the
        # validator recorded at first byte makes the server send a FULL 200
        # response — which restarts the .part below — if the file changed.
        if os.path.exists(meta):
            with open(meta) as f:
                validator = f.read().strip()
            if validator:
                req.add_header("If-Range", validator)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
    except urllib.error.HTTPError as e:
        if e.code == 416:  # range past EOF — .part is stale garbage
            os.remove(part)
            return download(url, dest, sha256, chunk, progress, timeout)
        raise
    mode = "ab" if offset and resp.status == 206 else "wb"
    if mode == "wb":
        offset = 0  # server ignored the range (or file changed); start over
        validator = (resp.headers.get("ETag")
                     or resp.headers.get("Last-Modified") or "")
        with open(meta, "w") as f:
            f.write(validator)
    total = resp.headers.get("Content-Length")
    total = (int(total) + offset) if total is not None else None
    with resp, open(part, mode) as f:
        while True:
            b = resp.read(chunk)
            if not b:
                break
            f.write(b)
            offset += len(b)
            if progress:
                progress(offset, total)
    # Completeness: a connection dropped mid-stream must not pass as a
    # finished file just because no sha256 is registered for this entry.
    if total is not None and offset != total:
        raise IOError(
            f"{url}: connection closed at {offset}/{total} bytes; "
            f"partial kept at {part} — re-run to resume")
    if sha256:
        got = sha256_file(part)
        if got != sha256:
            os.remove(part)
            raise IOError(f"sha256 mismatch for {url}: got {got}, "
                          f"want {sha256} (partial discarded)")
    os.replace(part, dest)
    if os.path.exists(meta):
        os.remove(meta)
    return dest


def extract(archive: str, out_dir: str) -> str:
    """tar/zip → ``out_dir`` (idempotent via a .extracted marker)."""
    marker = os.path.join(out_dir, ".extracted-" +
                          os.path.basename(archive))
    if os.path.exists(marker):
        return out_dir
    os.makedirs(out_dir, exist_ok=True)
    if archive.endswith(".zip"):
        with zipfile.ZipFile(archive) as z:
            z.extractall(out_dir)
    elif archive.endswith((".tar.gz", ".tgz", ".tar")):
        with tarfile.open(archive) as t:
            t.extractall(out_dir, filter="data")
    else:
        raise ValueError(f"unknown archive type: {archive}")
    with open(marker, "w") as f:
        f.write("ok\n")
    return out_dir


def fetch_dataset(name: str, cache_dir: str,
                  base_url: Optional[str] = None,
                  progress: Optional[Callable] = None,
                  verify: bool = True) -> DatasetSource:
    """Download + extract a registry dataset into ``cache_dir/<name>/``.

    ``base_url`` overrides the registry host (tests run a loopback HTTP
    server; an airgapped TPU pod can point at an internal mirror).  The
    registry sha256 is verified regardless of which host served the bytes —
    a mirror carries the *same* file; pass ``verify=False`` only for a
    mirror that re-packed the archive (CLI: ``--download-no-verify``).
    Returns the source record; the extracted tree is
    ``cache_dir/<name>/extracted``.
    """
    if name not in DATASETS:
        raise SystemExit(
            f"unknown dataset {name!r}; known: {sorted(DATASETS)}")
    src = DATASETS[name]
    if src.url is None:
        raise SystemExit(f"{name} cannot be auto-downloaded: {src.manual}")
    url = src.url
    if base_url:
        url = base_url.rstrip("/") + "/" + src.filename
    root = os.path.join(cache_dir, name)
    if verify and src.sha256 is None:
        print(f"warning: no registry sha256 for {name!r} — downloaded bytes "
              f"cannot be integrity-checked", flush=True)
    archive = download(url, os.path.join(root, src.filename),
                       sha256=src.sha256 if verify else None,
                       progress=progress)
    extract(archive, os.path.join(root, "extracted"))
    return src


def extracted_dir(name: str, cache_dir: str) -> str:
    return os.path.join(cache_dir, name, "extracted")
