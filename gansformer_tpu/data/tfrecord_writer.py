"""TFRecord *writer* — the other half of the reference's dataset tooling.

The reference's ``src/dataset_tool.py`` (SURVEY.md §2.2/§3.4, ~700 LoC)
converts image folders / CIFAR / LSUN into its multi-resolution TFRecord
layout (``<name>-r{02..10}.tfrecords`` + optional ``<name>-rXX.labels``).
This module produces that exact on-disk format — including valid masked
CRC32C framing, so files are readable by stock ``tf.data`` and therefore by
the reference itself — without any TensorFlow dependency (mirror of the
hand-rolled reader in ``data/dataset.py``).

Layout details matched to the reference:
* one ``.tfrecords`` file per level-of-detail, ``lod = log2(resolution)``,
  each holding every image box-downsampled to ``2**lod``;
* each record is a ``tf.train.Example`` with ``shape`` (int64 [C,H,W]) and
  ``data`` (raw CHW uint8 bytes);
* labels (if any) as ``<name>-rXX.labels`` — a ``np.save`` float32 array.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Optional, Sequence

import numpy as np

# ----------------------------------------------------------------------------
# CRC32C (Castagnoli, reflected poly 0x82F63B78) — TFRecord framing checksum.
# ----------------------------------------------------------------------------

def _make_crc_table() -> list:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_crc_table()

try:  # C implementation if available (export is CRC-bound in pure Python)
    from crc32c import crc32c as _crc32c_native  # type: ignore
except ImportError:
    try:
        from google_crc32c import value as _crc32c_native  # type: ignore
    except ImportError:
        _crc32c_native = None


def crc32c(data: bytes) -> int:
    if _crc32c_native is not None:
        return int(_crc32c_native(data))
    # this framework's own native host-ops lib (g++-at-first-use,
    # gansformer_tpu/native): ~1.4 GB/s vs ~1 MB/s pure Python
    from gansformer_tpu import native

    val = native.crc32c(data)
    if val is not None:
        return val
    # Pure-Python fallback: plain-list table (several× faster per byte
    # than indexing a numpy array); datasets are written once.
    crc = 0xFFFFFFFF
    table = _CRC_TABLE
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    rot = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------------------------
# Minimal protobuf encoding for tf.train.Example (inverse of the reader's
# _walk_proto; schema cited at data/dataset.py:185-195).
# ----------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _int64_list_feature(values: Sequence[int]) -> bytes:
    body = b"".join(_varint(1 << 3 | 0) + _varint(v) for v in values)
    return _len_delim(3, body)            # Feature.int64_list = 3


def _bytes_feature(data: bytes) -> bytes:
    return _len_delim(1, _len_delim(1, data))   # Feature.bytes_list.value


def encode_example_image(img_chw: np.ndarray) -> bytes:
    """CHW uint8 image → serialized tf.train.Example (reference schema)."""
    assert img_chw.dtype == np.uint8 and img_chw.ndim == 3
    feats = b""
    for key, feat in (("shape", _int64_list_feature(img_chw.shape)),
                      ("data", _bytes_feature(img_chw.tobytes()))):
        entry = _len_delim(1, key.encode()) + _len_delim(2, feat)
        feats += _len_delim(1, entry)     # Features.feature map entry
    return _len_delim(1, feats)           # Example.features


def write_record(f, payload: bytes) -> None:
    """TFRecord framing: u64 len, u32 masked-crc(len), payload,
    u32 masked-crc(payload)."""
    head = struct.pack("<Q", len(payload))
    f.write(head)
    f.write(struct.pack("<I", _masked_crc(head)))
    f.write(payload)
    f.write(struct.pack("<I", _masked_crc(payload)))


# ----------------------------------------------------------------------------
# Multi-resolution exporter
# ----------------------------------------------------------------------------

def _downsample_box2(img: np.ndarray) -> np.ndarray:
    """HWC uint8 → half resolution by 2x2 box filter (dataset_tool's
    downscale)."""
    h, w, c = img.shape
    x = img.reshape(h // 2, 2, w // 2, 2, c).astype(np.uint16)
    return ((x.sum(axis=(1, 3)) + 2) // 4).astype(np.uint8)


class TFRecordExporter:
    """Streams HWC uint8 images into the reference's multi-lod layout.

    Usage::

        with TFRecordExporter(out_dir, name, resolution) as ex:
            for img in images:          # HWC uint8
                ex.add_image(img)
            ex.add_labels(labels)       # optional [N, label_dim]
    """

    def __init__(self, out_dir: str, name: str, resolution: int,
                 min_lod: int = 2, all_lods: bool = True):
        r_log2 = resolution.bit_length() - 1
        if resolution != 2 ** r_log2 or resolution < 4:
            raise ValueError(f"resolution must be a power of 2 ≥ 4, "
                             f"got {resolution}")
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir, self.name = out_dir, name
        self.resolution, self.max_lod = resolution, r_log2
        lods = (range(r_log2, min_lod - 1, -1) if all_lods else [r_log2])
        self._files = {
            lod: open(os.path.join(
                out_dir, f"{name}-r{lod:02d}.tfrecords"), "wb")
            for lod in lods}
        self.num_images = 0

    def add_image(self, img_hwc: np.ndarray) -> None:
        if img_hwc.shape[:2] != (self.resolution, self.resolution):
            raise ValueError(
                f"image is {img_hwc.shape}, expected {self.resolution}²")
        img = np.ascontiguousarray(img_hwc, dtype=np.uint8)
        for lod in sorted(self._files, reverse=True):
            while img.shape[0] > 2 ** lod:
                img = _downsample_box2(img)
            write_record(self._files[lod],
                         encode_example_image(img.transpose(2, 0, 1)))
        self.num_images += 1

    def add_labels(self, labels: np.ndarray) -> None:
        path = os.path.join(self.out_dir,
                            f"{self.name}-r{self.max_lod:02d}.labels")
        with open(path, "wb") as f:
            np.save(f, labels.astype(np.float32))

    def close(self) -> None:
        for f in self._files.values():
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def export_images(images: Iterable[np.ndarray], out_dir: str, name: str,
                  resolution: int, labels: Optional[np.ndarray] = None,
                  all_lods: bool = True) -> int:
    with TFRecordExporter(out_dir, name, resolution, all_lods=all_lods) as ex:
        for img in images:
            ex.add_image(img)
        if labels is not None:
            ex.add_labels(labels)
        return ex.num_images


# ----------------------------------------------------------------------------
# LSUN lmdb → images; the dataset_tool ``create_lsun`` role.
# ----------------------------------------------------------------------------

def iter_lsun_lmdb(lmdb_dir: str, resolution: int,
                   max_images: Optional[int] = None):
    """Yields HWC uint8 images centre-cropped + resized to ``resolution``
    from an LSUN lmdb export (webp/jpg values, keys ignored).

    Gated on the ``lmdb`` package (not bundled with the framework — the
    reference's Dockerfile installs it ad hoc too); raises a clear error
    when missing.  Undecodable records are skipped with a count, matching
    dataset_tool's tolerance of LSUN's known corrupt entries."""
    try:
        import lmdb  # type: ignore
    except ImportError as e:
        raise ImportError(
            "LSUN conversion needs the 'lmdb' package (pip install lmdb); "
            "it is not bundled because only the LSUN path uses it") from e
    import io

    from PIL import Image

    env = lmdb.open(lmdb_dir, readonly=True, lock=False, readahead=False,
                    meminit=False)
    n, bad = 0, 0
    with env.begin(write=False) as txn:
        for _key, val in txn.cursor():
            if max_images is not None and n >= max_images:
                break
            try:
                img = Image.open(io.BytesIO(val)).convert("RGB")
            except Exception:
                bad += 1
                continue
            s = min(img.size)
            left = (img.size[0] - s) // 2
            top = (img.size[1] - s) // 2
            img = img.crop((left, top, left + s, top + s))
            img = img.resize((resolution, resolution), Image.LANCZOS)
            yield np.asarray(img, dtype=np.uint8)
            n += 1
    if bad:
        import sys

        print(f"[prepare_data] skipped {bad} undecodable LSUN records",
              file=sys.stderr)


# ----------------------------------------------------------------------------
# CIFAR-10 (python pickle batches) → arrays; the dataset_tool
# ``create_cifar10`` role.
# ----------------------------------------------------------------------------

def load_cifar10(data_dir: str):
    """Reads the 50k training batches (data_batch_1..5) from an extracted
    cifar-10-batches-py directory — the lineage's create_cifar10 uses the
    train split only.  Returns (images NHWC uint8, labels one-hot f32)."""
    import pickle

    imgs, labs = [], []
    names = [f"data_batch_{i}" for i in range(1, 6)]
    found = [n for n in names if os.path.exists(os.path.join(data_dir, n))]
    if not found:
        raise FileNotFoundError(
            f"no CIFAR-10 batches under {data_dir} (expected data_batch_1..5)")
    for n in found:
        with open(os.path.join(data_dir, n), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(np.asarray(d[b"data"], np.uint8)
                    .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labs.extend(d[b"labels"])
    images = np.concatenate(imgs)
    labels = np.zeros((len(labs), 10), np.float32)
    labels[np.arange(len(labs)), np.asarray(labs)] = 1.0
    return images, labels
