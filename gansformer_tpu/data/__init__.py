from gansformer_tpu.data.dataset import (
    Dataset,
    SyntheticDataset,
    NpzDataset,
    TFRecordDataset,
    ImageFolderDataset,
    PrefetchIterator,
    make_dataset,
)
from gansformer_tpu.data.device_prefetch import DevicePrefetcher
from gansformer_tpu.data.tfrecord_writer import TFRecordExporter, export_images
