from gansformer_tpu.data.dataset import (
    Dataset,
    SyntheticDataset,
    NpzDataset,
    TFRecordDataset,
    ImageFolderDataset,
    make_dataset,
)
