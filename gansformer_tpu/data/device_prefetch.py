"""Device-resident input prefetch — batches land in HBM before the loop
asks for them.

``PrefetchIterator`` (dataset.py) overlaps host-side decode/shuffle with
device compute, but the host→device transfer itself still ran on the
loop thread: every iteration paid a synchronous ``device_put`` (the
``h2d`` span) between dispatches.  ``DevicePrefetcher`` moves that
transfer to a second background thread and keeps a small ring
(``depth`` batches, default 2) already resident on the devices, so the
loop's ``h2d`` phase collapses to a queue pop of arrays that are
already where the step program wants them.

Pipeline shape (three stages, two queues)::

    decode thread ──host batches──▶ transfer thread ──device batches──▶ loop
    (PrefetchIterator)              (this module: put_fn +
                                     block_until_ready)

The transfer thread calls ``put_fn`` (the loop's sharding-aware
``device_put`` / ``make_array_from_process_local_data`` closure) and
then **blocks until the transfer settles**, so an item in the ring is
genuinely in HBM — the depth gauge never counts transfers still on the
PCIe/DMA queue, and ``data/h2d_ms`` measures real transfer time.
``jax`` dispatch is thread-safe; the put uses explicit ``NamedSharding``
objects, so no ambient-mesh context is needed on this thread.

Telemetry (obs/registry): ``data/device_queue_depth`` gauge (batches
resident in HBM waiting for the loop), ``data/h2d_ms`` histogram
(per-item transfer wall time on the background thread),
``data/device_batches_total`` counter.

Exceptions from the transfer thread (or the upstream iterator) surface
on the consumer's next ``get()``; ``close()`` joins the thread.  Close
the *upstream* iterator first — its end-of-stream sentinel is what
unblocks a transfer thread waiting on an empty host queue.

Stall watchdog (ISSUE 15): with ``stall_after_s > 0``, ``get()`` blocked
on an empty ring while the transfer thread makes no progress for that
long raises typed ``DataStalled`` — this is the layer that convicts a
wedged ``device_put`` (upstream decode stalls are convicted by
``PrefetchIterator``'s own watchdog and surface here as the stored
error).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

from gansformer_tpu.data.errors import stall_guarded_get
from gansformer_tpu.obs import registry as telemetry


class DevicePrefetcher:
    """Background-thread ``device_put`` ring over a host-batch iterator.

    ``iterator`` yields host-side items; ``put_fn(item)`` returns the
    device-resident form (arrays placed on their shardings).  The ring
    holds at most ``depth`` device items — HBM cost is
    ``depth × batch_bytes``, which at uint8 input batches is small next
    to model state (ffhq256 flagship: ~6 MB/batch at batch 32).

    The thread/queue/sentinel/close protocol deliberately mirrors
    ``dataset.PrefetchIterator`` (its upstream stage) — change one, check
    the other; ``tests/test_device_prefetch.py`` pins the layered
    teardown order.
    """

    _SENTINEL = object()

    def __init__(self, iterator: Iterator, put_fn: Callable,
                 depth: int = 2, stall_after_s: float = 0.0):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._finished = False
        self._error: Optional[BaseException] = None
        self._stall_after_s = float(stall_after_s or 0.0)
        self._last_progress = time.monotonic()
        self._g_depth = telemetry.gauge("data/device_queue_depth")
        self._c_batches = telemetry.counter("data/device_batches_total")
        self._c_stalls = telemetry.counter("data/stalls_total")
        self._h_h2d_ms = telemetry.histogram("data/h2d_ms")

        def _produce():
            import jax

            try:
                for item in iterator:
                    if self._stop.is_set():
                        return
                    t0 = time.perf_counter()
                    dev = put_fn(item)
                    # Settle the transfer HERE so the ring only holds
                    # batches that are really in device memory.
                    jax.block_until_ready(
                        [x for x in jax.tree_util.tree_leaves(dev)
                         if hasattr(x, "block_until_ready")])
                    self._h_h2d_ms.observe(
                        (time.perf_counter() - t0) * 1000.0)
                    while not self._stop.is_set():
                        try:
                            self._queue.put(dev, timeout=0.1)
                            self._last_progress = time.monotonic()
                            self._g_depth.set(self._queue.qsize())
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 — reraised on get()
                self._error = e
            finally:
                while not self._stop.is_set():
                    try:
                        self._queue.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(
            target=_produce, name="device-prefetch", daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def _pop(self):
        """Blocking ring pop under the shared stall-watchdog conviction
        rule (``errors.stall_guarded_get`` — one algorithm for both
        prefetch layers)."""
        return stall_guarded_get(
            self._queue, self._stall_after_s,
            lambda: self._last_progress, self._c_stalls,
            "device-prefetch transfer thread")

    def get(self):
        """Pop the next device-resident item (blocks if the transfer
        thread is behind — that block is the loop's ``data_wait``)."""
        if self._finished or self._stop.is_set():
            raise StopIteration
        item = self._pop()
        if item is self._SENTINEL:
            self._finished = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        self._g_depth.set(self._queue.qsize())
        self._c_batches.inc()
        return item

    __next__ = get

    def close(self) -> None:
        """Stop and join the transfer thread.  Idempotent.  If the
        thread is blocked pulling from an upstream ``PrefetchIterator``,
        close that upstream first (its close() wakes blocked consumers
        with a sentinel)."""
        self._stop.set()
        try:    # unblock a producer stuck on a full ring
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        self._g_depth.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
