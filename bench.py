"""Benchmark — training throughput on the flagship FFHQ-256 Duplex config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: ≥200 img/sec/chip on TPU v4 (BASELINE.json:5).

Measures the steady-state hot loop (D step + G step, with the lazy-reg
variants mixed in at their real cadence) on synthetic data, excluding
compilation, on however many chips are visible.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 200.0


def main() -> None:
    import jax
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    import dataclasses

    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"

    cfg = get_preset("ffhq256-duplex")
    # per-chip batch 8 (v4 HBM-friendly); global batch scales with chips
    batch = (8 * n_chips) if on_tpu else max(4, n_chips)
    if not on_tpu:
        # CPU fallback so the bench always emits a line: tiny proxy config.
        cfg = get_preset("clevr64-simplex")
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, dtype="float32"))
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, batch_size=batch))

    env = make_mesh(cfg.mesh)
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    state = jax.device_put(state, env.replicated())
    fns = make_train_steps(cfg, env, batch_size=batch)

    res = cfg.model.resolution
    imgs = np.random.RandomState(0).randint(
        0, 255, (batch, res, res, 3), dtype=np.uint8)
    imgs = jax.device_put(imgs, env.batch())
    rng = jax.random.PRNGKey(1)

    t = cfg.train

    def step(state, it):
        srng = jax.random.fold_in(rng, it)
        d_fn = fns.d_step_r1 if it % t.d_reg_interval == 0 else fns.d_step
        state, _ = d_fn(state, imgs, jax.random.fold_in(srng, 0))
        g_fn = fns.g_step_pl if it % t.g_reg_interval == 0 else fns.g_step
        state, _ = g_fn(state, jax.random.fold_in(srng, 1))
        return state

    # warmup: compile all four variants
    for it in range(max(t.d_reg_interval, t.g_reg_interval) + 1):
        state = step(state, it)
    jax.block_until_ready(state.step)

    iters = 30 if on_tpu else 5
    t0 = time.time()
    for it in range(iters):
        state = step(state, it)
    jax.block_until_ready(state.step)
    dt = time.time() - t0

    img_per_sec = iters * batch / dt
    img_per_sec_per_chip = img_per_sec / n_chips
    print(json.dumps({
        "metric": "train_img_per_sec_per_chip_ffhq256_duplex"
                  if on_tpu else "train_img_per_sec_per_chip_cpu_proxy",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
