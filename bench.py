"""Benchmark — training throughput on the flagship FFHQ-256 Duplex config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: ≥200 img/sec/chip on TPU v4 (BASELINE.json:5).

Design (VERDICT r2 item 1):
* A persistent XLA compilation cache under the repo
  (``.jax_compile_cache/``) makes every invocation after the first warm —
  cold compile of the second-order-grad step variants is minutes, warm is
  seconds.
* Each of the four step variants (d, d+r1, g, g+pl) is compiled AND timed
  separately, with a progress line on stderr after each — a timeout now
  shows exactly how far it got, and the per-phase timings are the PERF.md
  numbers.
* The inner process emits a (partial) JSON result line as soon as the
  steady-state pair (d, g) is measured, then a better line once the reg
  variants are in.  The outer process takes the LAST parseable line, even
  from a timed-out child — so a budget overrun still yields a TPU number.
* Throughput is cadence-weighted: per-iteration wall time =
  ``t_d·(1-1/16) + t_d_r1·(1/16) + t_g·(1-1/4) + t_g_pl·(1/4)`` at the
  reference lazy-reg intervals — i.e. the steady-state hot loop of
  SURVEY.md §3.1, not a no-reg fantasy number.
* On CPU fallback the JSON carries the TPU failure reason in a
  ``tpu_error`` field instead of dropping it.

Set ``GRAFT_BENCH_PROFILE=<dir>`` to wrap the timed section in a
``jax.profiler.trace`` (TensorBoard profile plugin format).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 200.0
_INNER_FLAG = "_GRAFT_BENCH_INNER"
_SELF = os.path.abspath(__file__)
_REPO = os.path.dirname(_SELF)
_CACHE_DIR = os.path.join(_REPO, ".jax_compile_cache")
_PHASES_OUT = os.path.join(_REPO, ".bench_phases.json")


def _log(msg: str) -> None:
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


_T0 = time.time()


def _run_inner() -> None:
    """The actual benchmark. Emits progress on stderr and one-or-more JSON
    lines on stdout (the last one wins)."""
    import dataclasses

    import jax

    # Persistent compilation cache: the single biggest fix for the r1/r2
    # "TPU bench never finishes compiling" failure.  Must be set before the
    # first compile.
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    n_chips = len(jax.devices())
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    _log(f"backend up: {n_chips}x {jax.devices()[0].device_kind} ({platform})")

    cfg = get_preset("ffhq256-duplex")
    # GRAFT_BENCH_BATCH sweeps per-chip batch (PERF.md §1b); default 8
    # matches the flagship preset's per-chip share.
    per_chip = int(os.environ.get("GRAFT_BENCH_BATCH", "8"))
    batch = (per_chip * n_chips) if on_tpu else max(4, n_chips)
    if not on_tpu:
        # CPU fallback so the bench always emits a line: tiny proxy config.
        cfg = get_preset("clevr64-simplex")
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, dtype="float32"))
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, batch_size=batch))
    metric = ("train_img_per_sec_per_chip_ffhq256_duplex" if on_tpu
              else "train_img_per_sec_per_chip_cpu_proxy")

    env = make_mesh(cfg.mesh)
    # jit the whole init: ONE compiled program instead of hundreds of small
    # eager dispatches (each a round-trip over the axon TPU tunnel).
    t_init = time.time()
    state = jax.jit(lambda k: create_train_state(cfg, k))(jax.random.PRNGKey(0))
    jax.block_until_ready(state.step)
    _log(f"state init in {time.time() - t_init:.1f}s")
    state = jax.device_put(state, env.replicated())

    res = cfg.model.resolution
    rng = jax.random.PRNGKey(1)
    t = cfg.train
    iters = 20 if on_tpu else 3

    profile_dir = os.environ.get("GRAFT_BENCH_PROFILE")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    best = 0.0

    def measure(bsz: int, emit_only_if_better: bool) -> float:
        """Compile+time the 4 lazy-reg phase variants at one global batch;
        emits JSON lines (the outer process takes the LAST parseable one,
        so emitting only-on-improvement keeps the best config's number)."""
        nonlocal state
        b_cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, batch_size=bsz))
        fns = make_train_steps(b_cfg, env, batch_size=bsz)
        imgs = jax.device_put(
            np.random.RandomState(0).randint(
                0, 255, (bsz, res, res, 3), dtype=np.uint8), env.batch())
        # Phase plan: steady-state pair first so a partial result exists
        # as early as possible; reg variants (second-order grads, the
        # compile hogs) after.
        phases = [
            ("d", fns.d_step, (imgs, rng)),
            ("g", fns.g_step, (rng,)),
            ("d_r1", fns.d_step_r1, (imgs, rng)),
            ("g_pl", fns.g_step_pl, (rng,)),
        ]
        timings: dict = {}
        compile_s: dict = {}

        def per_chip_now() -> float:
            # Cadence-weighted steady-state iteration time (SURVEY §3.1
            # hot loop).  With only (d, g) measured, reg steps are
            # approximated by the plain steps.
            td, tg = timings["d"], timings["g"]
            tdr = timings.get("d_r1", td)
            tgp = timings.get("g_pl", tg)
            it_time = (td * (1 - 1 / t.d_reg_interval)
                       + tdr / t.d_reg_interval
                       + tg * (1 - 1 / t.g_reg_interval)
                       + tgp / t.g_reg_interval)
            return bsz / it_time / n_chips

        def emit(partial: bool) -> None:
            per_chip = per_chip_now()
            if emit_only_if_better and partial:
                # The partial estimate approximates the (slower) reg
                # variants with the plain steps, so it is systematically
                # HIGH — emitting it in sweep mode could make an inflated
                # number from a worse config the final reported line.
                return
            if emit_only_if_better and per_chip <= best:
                _log(f"batch {bsz // n_chips}/chip: {per_chip:.1f} img/s — "
                     f"not better than {best:.1f}, not emitting")
                return
            out = {
                "metric": metric,
                "value": round(per_chip, 2),
                "unit": "img/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
                "n_chips": n_chips,
                "platform": platform,
                "batch_per_chip": bsz // n_chips,
                "phase_ms": {k: round(v * 1e3, 2) for k, v in timings.items()},
                "compile_s": {k: round(v, 1) for k, v in compile_s.items()},
            }
            if partial:
                out["partial"] = "reg variants not yet measured"
            print(json.dumps(out), flush=True)
            try:
                with open(_PHASES_OUT, "w") as f:
                    json.dump(out, f, indent=2)
            except OSError:
                pass

        st = state
        for name, fn, extra in phases:
            tc = time.time()
            compiled = fn.lower(st, *extra).compile()
            compile_s[name] = time.time() - tc
            _log(f"[b{bsz}] compiled {name} in {compile_s[name]:.1f}s")
            # warm-up call (also replaces donated state)
            st, _ = compiled(st, *extra)
            jax.block_until_ready(st.step)
            t0 = time.time()
            for _ in range(iters):
                st, _ = compiled(st, *extra)
            jax.block_until_ready(st.step)
            timings[name] = (time.time() - t0) / iters
            _log(f"[b{bsz}] timed {name}: {timings[name] * 1e3:.1f} ms/step")
            if name == "g":
                emit(partial=True)
        state = st
        emit(partial=False)
        return per_chip_now()

    try:
        best = measure(batch, emit_only_if_better=False)

        # Batch sweep (TPU only): larger per-chip batches usually feed the
        # MXU better; try each while the outer budget allows, emitting only
        # improvements so the final JSON line is the best measured config.
        if on_tpu:
            sweep = os.environ.get("GRAFT_BENCH_SWEEP", "16,32")
            budget = float(os.environ.get("GRAFT_BENCH_TPU_TIMEOUT", "900"))
            for per_chip_b in [int(s) for s in sweep.split(",") if s.strip()]:
                if per_chip_b * n_chips == batch:
                    continue
                if time.time() - _T0 > budget - 240:
                    _log(f"sweep: skipping batch {per_chip_b}/chip "
                         f"(outer budget nearly spent)")
                    break
                best = max(best, measure(per_chip_b * n_chips,
                                         emit_only_if_better=True))
    finally:
        if profile_dir:
            jax.profiler.stop_trace()


def _probe_tpu(timeout: float = 90.0) -> bool:
    """Cheap child that just initializes the ambient backend. Returns True
    iff a TPU platform comes up within the timeout (a wedged tunnel claim
    hangs forever — don't let the full bench budget pay for that)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and "tpu" in (proc.stdout or "")


def _attempt(env: dict, timeout: float):
    """Run the inner bench in a child; return (parsed JSON dict | None, err).

    Takes the LAST parseable JSON line — the inner emits incrementally, so
    even a timed-out child can yield a (partial) result."""
    env = dict(env)
    env[_INNER_FLAG] = "1"
    stdout, err = "", None
    try:
        proc = subprocess.run(
            [sys.executable, _SELF], env=env, cwd=_REPO,
            capture_output=True, text=True, timeout=timeout)
        stdout = proc.stdout or ""
        if proc.returncode != 0:
            err = (proc.stderr or "")[-2000:]
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout or ""
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        stderr_tail = e.stderr or ""
        if isinstance(stderr_tail, bytes):
            stderr_tail = stderr_tail.decode("utf-8", "replace")
        err = f"timeout after {timeout:.0f}s; progress: {stderr_tail[-1200:]}"
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            if err and "partial" in result:
                result["note"] = err[:500]
            return result, None
    return None, err or f"no JSON line in output: {stdout[-500:]!r}"


def main() -> None:
    if os.environ.get(_INNER_FLAG) == "1":
        _run_inner()
        return

    sys.path.insert(0, _REPO)
    from gansformer_tpu.utils.hostenv import sanitized_cpu_env

    # Cold compile of the reg variants was measured at ~11 min on the v5e
    # tunnel; warm (persistent cache) is under a minute.  The budget must
    # survive cold compile (VERDICT r2) — and thanks to incremental
    # emission even an overrun yields the steady-state TPU number.
    tpu_budget = float(os.environ.get("GRAFT_BENCH_TPU_TIMEOUT", "900"))
    tpu_err = None
    if _probe_tpu():
        result, tpu_err = _attempt(dict(os.environ), tpu_budget)
        if result is not None:
            print(json.dumps(result))
            return
    else:
        tpu_err = "TPU probe failed: backend did not come up within 90s"
    # sanitized CPU: PYTHONPATH cleared so the TPU sitecustomize can't
    # claim/hang the tunnel; proxy config keeps runtime small.
    result, cpu_err = _attempt(sanitized_cpu_env(1), 270.0)
    if result is not None:
        if tpu_err:
            result["tpu_error"] = tpu_err[:1000]
        print(json.dumps(result))
        return
    print(json.dumps({
        "metric": "train_img_per_sec_per_chip_ffhq256_duplex",
        "value": 0.0,
        "unit": "img/sec/chip",
        "vs_baseline": 0.0,
        "error": f"tpu: {tpu_err}; cpu: {cpu_err}"[:1500],
    }))


if __name__ == "__main__":
    main()
