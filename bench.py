"""Benchmark — training throughput on the flagship FFHQ-256 Duplex config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: ≥200 img/sec/chip on TPU v4 (BASELINE.json:5).

Measures the steady-state hot loop (D step + G step, with the lazy-reg
variants mixed in at their real cadence) on synthetic data, excluding
compilation, on however many chips are visible.

Hardened against backend-init failure: the outer process runs the actual
benchmark in a child, first with the ambient environment (the real TPU
path), then — if that fails or hangs — with a sanitized CPU environment
(PYTHONPATH cleared so the container's TPU-tunnel sitecustomize cannot
claim/hang the backend).  The outer process ALWAYS emits exactly one JSON
line, with an "error" field if every attempt failed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 200.0
_INNER_FLAG = "_GRAFT_BENCH_INNER"
_SELF = os.path.abspath(__file__)


def _run_inner() -> None:
    """The actual benchmark. Prints the one JSON line on success; any
    exception exits nonzero and the outer process falls back."""
    import dataclasses

    import jax
    import numpy as np

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"

    cfg = get_preset("ffhq256-duplex")
    # per-chip batch 8 (v4 HBM-friendly); global batch scales with chips
    batch = (8 * n_chips) if on_tpu else max(4, n_chips)
    if not on_tpu:
        # CPU fallback so the bench always emits a line: tiny proxy config.
        cfg = get_preset("clevr64-simplex")
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, dtype="float32"))
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, batch_size=batch))

    env = make_mesh(cfg.mesh)
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    state = jax.device_put(state, env.replicated())
    fns = make_train_steps(cfg, env, batch_size=batch)

    res = cfg.model.resolution
    imgs = np.random.RandomState(0).randint(
        0, 255, (batch, res, res, 3), dtype=np.uint8)
    imgs = jax.device_put(imgs, env.batch())
    rng = jax.random.PRNGKey(1)

    t = cfg.train

    def step(state, it):
        srng = jax.random.fold_in(rng, it)
        d_fn = fns.d_step_r1 if it % t.d_reg_interval == 0 else fns.d_step
        state, _ = d_fn(state, imgs, jax.random.fold_in(srng, 0))
        g_fn = fns.g_step_pl if it % t.g_reg_interval == 0 else fns.g_step
        state, _ = g_fn(state, jax.random.fold_in(srng, 1))
        return state

    # warmup: compile all four variants
    for it in range(max(t.d_reg_interval, t.g_reg_interval) + 1):
        state = step(state, it)
    jax.block_until_ready(state.step)

    iters = 30 if on_tpu else 5
    t0 = time.time()
    for it in range(iters):
        state = step(state, it)
    jax.block_until_ready(state.step)
    dt = time.time() - t0

    img_per_sec = iters * batch / dt
    img_per_sec_per_chip = img_per_sec / n_chips
    print(json.dumps({
        "metric": "train_img_per_sec_per_chip_ffhq256_duplex"
                  if on_tpu else "train_img_per_sec_per_chip_cpu_proxy",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(
            img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
    }))


def _probe_tpu(timeout: float = 90.0) -> bool:
    """Cheap child that just initializes the ambient backend. Returns True
    iff a TPU platform comes up within the timeout (a wedged tunnel claim
    hangs forever — don't let the full bench budget pay for that)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and "tpu" in (proc.stdout or "")


def _attempt(env: dict, timeout: float):
    """Run the inner bench in a child; return parsed JSON dict or None."""
    env = dict(env)
    env[_INNER_FLAG] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, _SELF], env=env,
            cwd=os.path.dirname(_SELF),
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, "timeout"
    if proc.returncode != 0:
        return None, (proc.stderr or "")[-2000:]
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"no JSON line in output: {(proc.stdout or '')[-500:]!r}"


def main() -> None:
    if os.environ.get(_INNER_FLAG) == "1":
        _run_inner()
        return

    sys.path.insert(0, os.path.dirname(_SELF))
    from gansformer_tpu.utils.hostenv import sanitized_cpu_env

    attempts = []
    if _probe_tpu():
        # ambient env: the real TPU path (axon plugin); generous budget
        # for first-compile of all four step variants.
        attempts.append((dict(os.environ), 420.0))
    # sanitized CPU: PYTHONPATH cleared so the TPU sitecustomize can't
    # claim/hang the tunnel; proxy config keeps runtime small.
    attempts.append((sanitized_cpu_env(1), 270.0))
    last_err = None
    for env, timeout in attempts:
        result, err = _attempt(env, timeout)
        if result is not None:
            print(json.dumps(result))
            return
        last_err = err
    print(json.dumps({
        "metric": "train_img_per_sec_per_chip_ffhq256_duplex",
        "value": 0.0,
        "unit": "img/sec/chip",
        "vs_baseline": 0.0,
        "error": (last_err or "all attempts failed")[:1500],
    }))


if __name__ == "__main__":
    main()
