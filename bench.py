"""Benchmark — training throughput on the flagship FFHQ-256 Duplex config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: ≥200 img/sec/chip on TPU v4 (BASELINE.json:5).

Design (VERDICT r2 item 1):
* A persistent XLA compilation cache under the repo
  (``.jax_compile_cache/``) makes every invocation after the first warm —
  cold compile of the second-order-grad step variants is minutes, warm is
  seconds.
* Each of the four step variants (d, d+r1, g, g+pl) is compiled AND timed
  separately, with a progress line on stderr after each — a timeout now
  shows exactly how far it got, and the per-phase timings are the PERF.md
  numbers.
* The inner process emits a (partial) JSON result line as soon as the
  steady-state pair (d, g) is measured, then a better line once the reg
  variants are in.  The outer process takes the LAST parseable line, even
  from a timed-out child — so a budget overrun still yields a TPU number.
* Throughput is cadence-weighted: per-iteration wall time =
  ``t_d·(1-1/16) + t_d_r1·(1/16) + t_g·(1-1/4) + t_g_pl·(1/4)`` at the
  reference lazy-reg intervals — i.e. the steady-state hot loop of
  SURVEY.md §3.1, not a no-reg fantasy number.
* On CPU fallback the JSON carries the TPU failure reason in a
  ``tpu_error`` field instead of dropping it, and ``vs_baseline`` is null —
  a clevr64 CPU proxy has no meaningful ratio against the ffhq256 TPU
  target (VERDICT r3 weak #6).

Self-validation (VERDICT r3 item 1 — the r3 artifact recorded 1022
img/s/chip, which implies ~300% of a v5e's bf16 peak; a bench that can
emit that must police itself):
* Per-phase FLOPs come from XLA cost analysis on the exact compiled
  program; with the device's bf16 peak (by ``device_kind``) the JSON
  reports per-phase and cadence-weighted **MFU**.  ``mfu ≥ 1`` is flagged
  ``suspect`` — faster-than-physics numbers are reported as harness
  failures, never as results.
* Phase-time consistency: ``t(d_r1)/t(d)`` must track the FLOPs ratio
  (±35%); a reg step measured as cheap as the plain step means the timer
  is not measuring the device.
* After each timed loop, a real device→host fetch of a loss scalar
  data-dependent on the final step (``jax.device_get``) measures the
  sync tail: a relay acking ``block_until_ready`` early cannot fake the
  value, so a sync tail comparable to the supposed loop time means the
  loop wasn't finished when the clock stopped — flagged.  The reported
  times are the block clock (one fetch RTT is NOT amortized into them).
* A linearity probe re-times the ``d`` phase at 2× iterations: constant
  time under doubled work (ratio ≪ 1) means acks, not execution.
* A trace witness wraps a short ``d`` window in ``jax.profiler.trace``
  and parses the xplane's DEVICE plane (utils/profparse.py): device busy
  time far above the claimed wall time means the wall clock stopped
  before the chip did.  OPT-IN via GRAFT_BENCH_TRACE=1 and runs dead
  last: the tracer was observed (r4) to hang over the axon tunnel AND to
  wedge the backend claim for subsequent processes when killed mid-trace.
* Device identity (``device_kind``, device count, process count, HBM
  stats) is embedded so "was this really one chip?" is answerable from
  the artifact alone.
* The batch sweep is OOM-guarded: an XLA RESOURCE_EXHAUSTED records
  ``sweep_stopped: "oom at batch N/chip"`` in the final JSON instead of
  killing the child after the budget is spent (VERDICT r3 weak #4).

Set ``GRAFT_BENCH_PROFILE=<dir>`` to wrap the timed section in a
``jax.profiler.trace`` (TensorBoard profile plugin format).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 200.0
_INNER_FLAG = "_GRAFT_BENCH_INNER"
_SCALING_FLAG = "_GRAFT_BENCH_SCALING"
_SELF = os.path.abspath(__file__)
_REPO = os.path.dirname(_SELF)
_PHASES_OUT = os.path.join(_REPO, ".bench_phases.json")
# Stable copy of the latest --scaling artifact (the numbered
# MULTICHIP_r* file is the round record; the battery copies this one).
_SCALING_OUT = os.path.join(_REPO, ".scaling_bench.json")
# graftcomms attribution artifact (gansformer-lint --trace --json-out;
# the battery's graftcomms stage refreshes it) — when present, the
# bench artifact carries an expected-DP-scaling-efficiency section.
_COMMS_JSON = os.environ.get(
    "GRAFT_COMMS_JSON", os.path.join(_REPO, ".comms_attribution.json"))
# Order-of-magnitude per-chip ICI budget (~v4/v5e class); the scaling
# section reports the assumption so a reader can re-scale it.
ICI_BYTES_PER_S = 9.0e10


def _log(msg: str) -> None:
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


_T0 = time.time()


def _is_oom(e: BaseException) -> bool:
    return "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)


def build_phase_artifact(*, metric: str, on_tpu: bool, n_chips: int,
                         platform: str, bsz: int, timings: dict, flops: dict,
                         fetch_s: dict, compile_s: dict, identity: dict,
                         peak, d_reg_interval: int, g_reg_interval: int,
                         iters: int, linearity: dict, device_kind: str,
                         partial: bool, device_ms: dict = None) -> dict:
    """Measurement numbers → the phase-weighted artifact dict (VERDICT r4
    weak #4: the logic that decides whether a number is real, as a PURE
    function on plain dicts — unit-testable without a device).

    Computes the cadence-weighted img/s/chip, per-phase + weighted MFU,
    and runs the physics/consistency checks (``find_suspects``); a result
    failing any check carries ``suspect`` instead of being presented
    clean.  The partial form (only d+g timed) approximates reg phases
    with the plain ones — systematically HIGH, so it is labeled."""
    from gansformer_tpu.utils.benchcheck import (
        cadence_weighted, find_suspects, mfu as mfu_of)

    def weighted(vals: dict) -> float:
        return cadence_weighted(vals, d_reg_interval, g_reg_interval)

    per_chip = bsz / weighted(timings) / n_chips
    out = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        # A clevr64 CPU proxy has no meaningful ratio against the
        # ffhq256 TPU baseline (VERDICT r3 weak #6): null, not noise.
        "vs_baseline": (round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4)
                        if on_tpu else None),
        "n_chips": n_chips,
        "platform": platform,
        "batch_per_chip": bsz // n_chips,
        "phase_ms": {k: round(v * 1e3, 2) for k, v in timings.items()},
        "fetch_sync_tail_s": {k: round(v, 3) for k, v in fetch_s.items()},
        "compile_s": {k: round(v, 1) for k, v in compile_s.items()},
        "device": identity,
    }
    if not on_tpu:
        out["vs_baseline_note"] = (
            "cpu proxy (clevr64-simplex) — not comparable to the "
            "ffhq256 TPU target; no ratio reported")
    if flops:
        out["phase_gflops_per_chip"] = {
            k: round(v / 1e9, 1) for k, v in flops.items()}
    if device_ms:
        attach_device_ms(out, device_ms, flops, peak)
    if peak:
        out["peak_bf16_tflops_per_chip"] = peak
        out["phase_mfu"] = {
            k: round(flops[k] / timings[k] / (peak * 1e12), 4)
            for k in timings if k in flops}
        if not partial and all(k in flops for k in timings):
            out["mfu"] = round(
                mfu_of(weighted(flops), weighted(timings), peak), 4)
    sus = find_suspects(
        timings, flops, d_reg_interval=d_reg_interval,
        g_reg_interval=g_reg_interval, peak=peak, device_kind=device_kind,
        iters=iters, fetch_tails=fetch_s, linearity=linearity)
    if sus:
        out["suspect"] = sus
    if partial:
        out["partial"] = "reg variants not yet measured"
    return out


def attach_device_ms(out: dict, device_ms: dict, flops: dict,
                     peak) -> dict:
    """Profiler-derived per-iteration DEVICE time next to the wall
    number (ISSUE 8): wall ms is what the host clock claims, device ms
    is what the chip executed — the r3 retraction is the reason both
    ride the artifact.  THE one place that formats ``phase_device_ms``
    / ``phase_device_mfu`` (pure; ``build_phase_artifact`` and the
    trace witness both call it, so the tested path IS the shipped
    path).  Mutates and returns ``out``."""
    out["phase_device_ms"] = {k: round(v, 2) for k, v in device_ms.items()}
    if peak:
        mfu = {k: round(flops[k] / (device_ms[k] / 1e3) / (peak * 1e12), 4)
               for k in device_ms if k in flops and device_ms[k] > 0}
        if mfu:
            out["phase_device_mfu"] = mfu
    return out


def build_expected_scaling(comms_payload: dict, phase_ms: dict,
                           ici_bytes_per_s: float = ICI_BYTES_PER_S):
    """graftcomms attribution (``scaling_bytes_per_device``: per-entry
    predicted wire bytes vs chip count) + this run's measured per-phase
    ms → expected data-parallel scaling efficiency per phase per chip
    count (PURE; the efficiency model lives in
    analysis/trace/collective_flow.py — serial no-overlap ring, a floor
    not a forecast).  Returns None when the artifact and the timings
    share no phase, or when the capture never compiled a ≥2-device
    mesh (a single-chip tunnel window records zero collectives —
    presenting that as perfect scaling would be exactly the
    device-starved false-clean the artifact's coverage fields exist to
    prevent) — ROADMAP item 2's "report scaling efficiency vs chip
    count" before any multi-chip hardware exists."""
    from gansformer_tpu.analysis.trace.collective_flow import (
        scaling_efficiency)

    if not any(int(n) >= 2
               for n in comms_payload.get("mesh_sizes_compiled") or []):
        return None

    phase_of = {"d_step": "d", "d_step_r1": "d_r1",
                "g_step": "g", "g_step_pl": "g_pl"}
    per_phase: dict = {}
    for entry, per_chip in (comms_payload.get("scaling_bytes_per_device")
                            or {}).items():
        tail = entry.split(".", 1)[1] if "." in entry else entry
        phase = phase_of.get(tail.split("[", 1)[0])
        if phase is None or phase not in phase_ms or phase in per_phase:
            continue
        step_s = phase_ms[phase] / 1e3
        per_phase[phase] = {
            c: round(scaling_efficiency(int(w), step_s, ici_bytes_per_s), 4)
            for c, w in sorted(per_chip.items(), key=lambda kv: int(kv[0]))}
    if not per_phase:
        return None
    return {
        "assumed_ici_bytes_per_s": ici_bytes_per_s,
        "model": "serial no-overlap ring comms on top of the measured "
                 "phase time — an efficiency floor, not a forecast",
        "per_phase_efficiency": per_phase,
        "comms_profile": comms_payload.get("trace_profile"),
    }


def _hbm_snapshot():
    """Max-over-local-devices HBM stats right now (the same aggregation
    the heartbeat records — ``obs/heartbeat.hbm_device_stats``), or None
    on backends that don't report (CPU).  Attached fresh at every
    artifact emission so ``hbm.peak_bytes`` reflects the programs
    actually measured — the FFHQ-1024 fit evidence (ISSUE 8
    satellite)."""
    try:
        from gansformer_tpu.obs.heartbeat import hbm_device_stats
    except Exception:
        return None
    out = hbm_device_stats()
    if out is not None and not out["bytes_limit"]:
        out = {k: v for k, v in out.items() if k != "bytes_limit"}
    return out


def _load_comms_payload(path: str = None):
    path = path or _COMMS_JSON
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# --- scaling-efficiency mode (ISSUE 7) --------------------------------------
# ``bench.py --scaling``: run the four step phases on data meshes of
# 1/2/4 devices (weak scaling — fixed per-chip batch) and CLOSE the
# loop the graftcomms table opened: the compiled programs' collectives,
# per-device cost-analysis FLOPs, measured per-phase img/s/chip
# efficiency, and the ring-model floor, all in one MULTICHIP artifact.
# On a forced-CPU host the virtual devices timeshare the same cores, so
# the MEASURED efficiency is not hardware-meaningful — the real signal
# there is (a) per-device FLOPs dropping ~1/n (compute genuinely
# shards) and (b) the gradient all-reduce being present at n ≥ 2
# (zero collectives on a multi-device mesh is the ISSUE 7 regression).

_SCALING_PHASE_ENTRY = {"d": "d_step", "d_r1": "d_step_r1",
                        "g": "g_step", "g_pl": "g_step_pl"}


def measure_scaling_mesh(cfg_base, n: int, per_chip_batch: int,
                         iters: int) -> dict:
    """Compile + time the four phase variants on an n-device data mesh
    (weak scaling: global batch = per_chip_batch × n).  Returns one
    per-mesh record: phase ms, per-device cost-analysis FLOPs, the
    compiled programs' collective inventory (+ ring wire bytes), and
    per-phase img/s/chip."""
    import dataclasses

    import jax
    import numpy as np

    from gansformer_tpu.analysis.trace.collective_flow import (
        comms_record, parse_collectives)
    from gansformer_tpu.core.config import MeshConfig
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps
    from gansformer_tpu.utils.benchcheck import flops_of

    bsz = per_chip_batch * n
    cfg = dataclasses.replace(
        cfg_base,
        train=dataclasses.replace(cfg_base.train, batch_size=bsz),
        mesh=MeshConfig(data=n))
    env = make_mesh(cfg.mesh, devices=jax.devices()[:n])
    fns = make_train_steps(cfg, env, batch_size=bsz)
    state = jax.jit(lambda k: create_train_state(cfg, k))(
        jax.random.PRNGKey(0))
    jax.block_until_ready(state.step)
    state = jax.device_put(state, env.replicated())
    res, ch = cfg.model.resolution, cfg.model.img_channels
    imgs = jax.device_put(
        np.random.RandomState(0).randint(0, 255, (bsz, res, res, ch),
                                         dtype=np.uint8), env.batch())
    rng = jax.random.PRNGKey(1)
    phases = [("d", fns.d_step, (imgs, rng)),
              ("g", fns.g_step, (rng,)),
              ("d_r1", fns.d_step_r1, (imgs, rng)),
              ("g_pl", fns.g_step_pl, (rng,))]
    rec = {"devices": n, "global_batch": bsz,
           "per_chip_batch": per_chip_batch,
           "phase_ms": {}, "phase_gflops_per_device": {},
           "img_per_sec_per_chip": {}, "collectives": {},
           "wire_bytes_per_device": {}, "comms_records": []}
    st = state
    with env.activate():
        for name, fn, extra in phases:
            tc = time.time()
            compiled = fn.lower(st, *extra).compile()
            _log(f"[scaling n={n}] compiled {name} in "
                 f"{time.time() - tc:.1f}s")
            fl = flops_of(compiled)
            if fl:
                rec["phase_gflops_per_device"][name] = round(fl / 1e9, 4)
            ops = parse_collectives(compiled.as_text(), default_group=n)
            crec = comms_record(f"steps.{_SCALING_PHASE_ENTRY[name]}"
                                f"[scaling]", n, ops, {})
            rec["comms_records"].append(crec)
            rec["collectives"][name] = {
                k: dict(v) for k, v in crec["collectives"].items()}
            rec["wire_bytes_per_device"][name] = \
                crec["total_wire_bytes_per_device"]
            st, _ = compiled(st, *extra)      # warm-up (donates)
            jax.block_until_ready(st.step)
            t0 = time.time()
            for _ in range(iters):
                st, _ = compiled(st, *extra)
            jax.block_until_ready(st.step)
            per_it = (time.time() - t0) / iters
            rec["phase_ms"][name] = round(per_it * 1e3, 3)
            rec["img_per_sec_per_chip"][name] = round(
                bsz / per_it / n, 3)
            _log(f"[scaling n={n}] {name}: {per_it * 1e3:.1f} ms/it, "
                 f"{rec['img_per_sec_per_chip'][name]:.1f} img/s/chip, "
                 f"wire {rec['wire_bytes_per_device'][name]} B/dev")
    return rec


def build_scaling_artifact(per_mesh: list, *, platform: str,
                           device_kind: str, config_name: str,
                           iters: int,
                           ici_bytes_per_s: float = ICI_BYTES_PER_S,
                           mesh_sizes_requested: list = None) -> dict:
    """Per-mesh measurement records → the MULTICHIP scaling artifact
    (PURE — unit-tested without devices, tests/test_bench_artifacts).

    Computes per-phase measured efficiency vs the 1-device member
    (img/s/chip ratio — the weak-scaling definition), the ring-model
    efficiency FLOOR per mesh (serial no-overlap comms on top of the
    1-device phase time), and embeds a graftcomms-compatible payload
    (``mesh_sizes_compiled`` + ``scaling_bytes_per_device``) so
    ``build_expected_scaling`` accepts the artifact as a comms source.
    Flags the ISSUE 7 regression in-line: a train phase with zero
    all-reduces on a multi-device mesh gets a ``suspect`` entry."""
    from gansformer_tpu.analysis.trace.collective_flow import (
        scaling_efficiency, scaling_report)

    by_n = {int(r["devices"]): r for r in per_mesh}
    sizes = sorted(by_n)
    requested = sorted(int(n) for n in (mesh_sizes_requested
                                        if mesh_sizes_requested is not None
                                        else sizes))
    if not sizes:
        # nothing measured (every requested mesh skipped on a device-
        # starved backend) — an honest empty artifact, not a traceback
        return {
            "metric": "scaling_efficiency_per_phase",
            "kind": "scaling_bench", "platform": platform,
            "device_kind": device_kind, "config": config_name,
            "iters": iters, "mesh_sizes": [],
            "per_mesh": {}, "trace_profile": "scaling-bench",
            "mesh_sizes_requested": requested,
            "mesh_sizes_compiled": [],
            "scaling_bytes_per_device": {},
            "assumed_ici_bytes_per_s": ici_bytes_per_s,
            "suspect": ["no mesh size could be measured (requested "
                        f"{requested}, backend too small) — nothing "
                        f"here shows scaling"],
        }
    largest = by_n[sizes[-1]]
    base = by_n.get(1)
    out = {
        "metric": "scaling_efficiency_per_phase",
        "kind": "scaling_bench",
        "platform": platform,
        "device_kind": device_kind,
        "config": config_name,
        "iters": iters,
        "mesh_sizes": sizes,
        "per_mesh": {str(n): {k: v for k, v in by_n[n].items()
                              if k != "comms_records"} for n in sizes},
        # graftcomms-payload-compatible section (build_expected_scaling
        # consumes exactly these keys).  requested vs compiled kept
        # DISTINCT, same honesty contract as the PR-6 comms payload: a
        # device-starved capture must read as partial coverage.
        "trace_profile": "scaling-bench",
        "mesh_sizes_requested": requested,
        "mesh_sizes_compiled": sizes,
        "scaling_bytes_per_device": scaling_report(
            largest.get("comms_records", [])),
        "assumed_ici_bytes_per_s": ici_bytes_per_s,
    }
    suspects = []
    if base is not None:
        eff = {}
        floor = {}
        for n in sizes:
            if n == 1:
                continue
            rec = by_n[n]
            eff[str(n)] = {
                ph: round(v / base["img_per_sec_per_chip"][ph], 4)
                for ph, v in rec["img_per_sec_per_chip"].items()
                if base["img_per_sec_per_chip"].get(ph)}
            floor[str(n)] = {
                ph: round(scaling_efficiency(
                    int(rec["wire_bytes_per_device"].get(ph, 0)),
                    base["phase_ms"][ph] / 1e3, ici_bytes_per_s), 4)
                for ph in rec["phase_ms"] if ph in base["phase_ms"]}
        if eff:
            out["per_phase_efficiency"] = eff
            out["ring_floor_efficiency"] = floor
    for n in sizes:
        if n <= 1:
            continue
        for ph, kinds in by_n[n]["collectives"].items():
            if "all-reduce" not in kinds:
                suspects.append(
                    f"{ph}@{n}dev: zero all-reduces on a multi-device "
                    f"data mesh — replicated compute (the ISSUE 7 "
                    f"regression); scaling numbers for this phase are "
                    f"N copies of the same work")
    if max(sizes) < 2:
        suspects.append("single-device capture only: no multi-device "
                        "mesh was measured, nothing here shows scaling")
    if platform != "tpu":
        out["cpu_note"] = (
            "forced host-platform devices timeshare the same CPU cores: "
            "measured efficiency is NOT hardware-meaningful off-TPU; "
            "trust phase_gflops_per_device (~1/n proves compute shards) "
            "and the collective inventory, and read ring_floor_"
            "efficiency as the model prediction for real chips")
    if suspects:
        out["suspect"] = suspects
    return out


def _next_multichip_path() -> str:
    """Next free MULTICHIP_rNN.json at the repo root (the driver's
    numbered-round convention; override with GRAFT_SCALING_OUT)."""
    override = os.environ.get("GRAFT_SCALING_OUT")
    if override:
        return override if os.path.isabs(override) \
            else os.path.join(_REPO, override)
    i = 1
    while os.path.exists(os.path.join(_REPO, f"MULTICHIP_r{i:02d}.json")):
        i += 1
    return os.path.join(_REPO, f"MULTICHIP_r{i:02d}.json")


def run_scaling(cfg_base, mesh_sizes, per_chip_batch: int, iters: int,
                out_path: str = None, config_name: str = None) -> dict:
    """The --scaling library core (tests call it directly): measure each
    mesh size, build the artifact, write it and return it.

    The artifact is re-built and re-written after EVERY mesh member
    (build is pure and cheap; the ffhq256 compiles are minutes each),
    so a killed-over-budget TPU window still leaves the partial capture
    on disk — same incremental-emission discipline as the phase bench.
    With the default path both the numbered MULTICHIP file and the
    stable ``.scaling_bench.json`` copy (the battery's window artifact)
    are written; an explicit ``out_path`` (tests) writes ONLY there, so
    a slow-suite run can never clobber a real TPU capture's stable
    copy."""
    import jax

    def build(per_mesh):
        out = build_scaling_artifact(
            per_mesh, platform=jax.devices()[0].platform,
            device_kind=jax.devices()[0].device_kind,
            config_name=config_name or cfg_base.name, iters=iters,
            mesh_sizes_requested=list(mesh_sizes))
        # the artifact is itself a valid comms payload: attach the
        # expected-scaling section from its own capture + 1-device times
        base = next((r for r in per_mesh if r["devices"] == 1), None)
        if base is not None:
            scal = build_expected_scaling(out, base["phase_ms"])
            if scal is not None:
                out["expected_scaling"] = scal
        return out

    path = out_path or _next_multichip_path()
    targets = (path,) if out_path else (path, _SCALING_OUT)

    def write(out):
        for p in targets:
            try:
                with open(p, "w") as f:
                    json.dump(out, f, indent=1, sort_keys=True)
                    f.write("\n")
            except OSError as e:
                _log(f"[scaling] could not write {p}: {e}")

    per_mesh = []
    out = None
    for n in mesh_sizes:
        if n > len(jax.devices()):
            _log(f"[scaling] skipping {n}-device mesh "
                 f"(have {len(jax.devices())})")
            continue
        per_mesh.append(measure_scaling_mesh(cfg_base, n, per_chip_batch,
                                             iters))
        out = build(per_mesh)
        write(out)
    if out is None:           # nothing measurable: still emit honestly
        out = build(per_mesh)
        write(out)
    out["artifact"] = os.path.basename(path)
    return out


def _run_scaling_inner() -> None:
    """Child-process driver for --scaling: pick the platform-appropriate
    config, measure mesh sizes 1/2/4 (clamped to the backend's device
    count), emit ONE JSON line."""
    import dataclasses

    import jax

    sys.path.insert(0, _REPO)
    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache(_REPO)

    from gansformer_tpu.core.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig,
        TrainConfig, get_preset)

    on_tpu = jax.devices()[0].platform == "tpu"
    n_dev = len(jax.devices())
    _log(f"[scaling] backend: {n_dev}x {jax.devices()[0].device_kind}")
    if on_tpu:
        cfg = get_preset("ffhq256-duplex")
        per_chip = int(os.environ.get("GRAFT_SCALING_BATCH", "8"))
        iters = int(os.environ.get("GRAFT_SCALING_ITERS", "10"))
    else:
        # CPU proxy: the micro structure — the artifact's value off-TPU
        # is the sharded-FLOPs + collective evidence, not wall time
        cfg = ExperimentConfig(
            name="scaling-micro",
            model=ModelConfig(resolution=16, components=2, latent_dim=16,
                              w_dim=16, mapping_dim=16, mapping_layers=2,
                              fmap_base=64, fmap_max=32,
                              attention="simplex", attn_start_res=8,
                              attn_max_res=8, mbstd_group_size=4),
            train=TrainConfig(batch_size=4, total_kimg=1, d_reg_interval=2,
                              g_reg_interval=2, pl_batch_shrink=2,
                              ema_kimg=0.01, style_mixing_prob=0.5),
            data=DataConfig(resolution=16, source="synthetic"),
            mesh=MeshConfig())
        per_chip = int(os.environ.get("GRAFT_SCALING_BATCH", "4"))
        iters = int(os.environ.get("GRAFT_SCALING_ITERS", "2"))
    sizes = [n for n in (1, 2, 4) if n <= n_dev]
    out = run_scaling(cfg, sizes, per_chip, iters)
    slim = {k: v for k, v in out.items()
            if k not in ("per_mesh", "scaling_bytes_per_device")}
    print(json.dumps({**slim, "per_mesh_in_artifact": True}), flush=True)


def _run_scaling_outer() -> None:
    """Outer --scaling: TPU when the probe says the tunnel is alive,
    else a sanitized 4-virtual-CPU-device child (the tier-1 / laptop
    path — multi-device meshes need forced host devices)."""
    sys.path.insert(0, _REPO)
    from gansformer_tpu.utils.hostenv import sanitized_cpu_env

    budget = float(os.environ.get("GRAFT_SCALING_TIMEOUT", "900"))
    if _probe_tpu():
        env = dict(os.environ)
    else:
        _log("scaling: no TPU — forced 4-virtual-CPU-device child")
        env = sanitized_cpu_env(4)
        budget = float(os.environ.get("GRAFT_SCALING_TIMEOUT", "600"))
    env[_SCALING_FLAG] = "1"
    result, err = _attempt(env, budget)
    if result is not None:
        print(json.dumps(result))
        return
    print(json.dumps({
        "metric": "scaling_efficiency_per_phase",
        "kind": "scaling_bench",
        "error": (err or "no JSON from scaling child")[:1500]}))


def steady_state_time(step, carry, n_it):
    """THE validated steady-state timing loop (the r3-retraction
    discipline), shared by the phase bench below and the satellite
    benches (scripts/bench_pallas_attention.py) so every published
    number inherits the same early-ack defenses.

    ``step``: carry → (carry, out) — a donated-state train step chains
    its state through ``carry``; a stateless kernel bench passes
    ``carry=None`` and returns ``(None, result)``.

    Returns ``(carry, per_it_s, tail_s)``:
    * ``per_it_s`` — wall seconds per call to ``jax.block_until_ready``
      (the reported block clock; one fetch RTT is NOT amortized in).
    * ``tail_s``  — the post-block sync tail of a REAL device→host fetch
      of a scalar data-dependent on the final call: an ack-early relay
      cannot fake the value, so a tail comparable to the timed loop
      means the loop wasn't finished when the clock stopped
      (benchcheck.find_suspects / single_timer_suspects flag it).

    Callers wanting the linearity defense re-invoke at 2× ``n_it`` and
    hand both per-it times to the suspect check.
    """
    import jax
    import numpy as np

    t0 = time.time()
    out = None
    for _ in range(n_it):
        carry, out = step(carry)
    jax.block_until_ready(carry if carry is not None else out)
    t_block = time.time()
    leaf = jax.tree_util.tree_leaves(out)[0]
    if getattr(leaf, "ndim", 0):
        # Device-index ONE element before fetching: the kernel benches'
        # first leaf is a full gradient array, and a whole-tensor
        # device_get would make tail_s measure host-transfer bandwidth
        # instead of the sync tail the early-ack defense keys off.
        leaf = leaf[(0,) * leaf.ndim]
    float(np.asarray(jax.device_get(leaf)).ravel()[0])
    return carry, (t_block - t0) / n_it, time.time() - t_block


def build_cycle_artifact(*, metric: str, n_chips: int, platform: str,
                         bsz: int, k_cyc: int, per_call_s: float,
                         tail_s: float, n_calls: int, compile_s: float,
                         identity: dict, peak, cycle_flops,
                         device_kind: str) -> dict:
    """Fused-cycle measurement → artifact dict (pure, unit-testable).

    ``cycle_flops`` is the per-call figure derived from the PHASE cost
    analyses × cadence × cycle length (the cycle program's own cost
    analysis counts its scan bodies once, not × trip count — see
    ``_BenchSession.measure_cycle``); None when the phase analyses are
    unavailable.  Carries its own suspect checks (physics + early-ack
    tail) so a bad cycle number can never be emitted clean."""
    per_chip = bsz * k_cyc / per_call_s / n_chips
    out = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
        "method": f"fused_cycle_{k_cyc}",
        "n_chips": n_chips,
        "platform": platform,
        "batch_per_chip": bsz // n_chips,
        "cycle_ms": round(per_call_s * 1e3, 2),
        "fetch_sync_tail_s": {"cycle": round(tail_s, 3)},
        "compile_s": {"cycle": round(compile_s, 1)},
        "device": identity,
    }
    sus = []
    if cycle_flops:
        out["cycle_gflops_per_chip"] = round(cycle_flops / 1e9, 1)
        out["cycle_flops_source"] = \
            "phase cost analysis x cadence (scan bodies count once)"
        if peak:
            m = cycle_flops / per_call_s / (peak * 1e12)
            out["peak_bf16_tflops_per_chip"] = peak
            out["mfu"] = round(m, 4)
            if m >= 1.0:
                sus.append(
                    f"mfu {m:.2f} >= 1.0 — implied throughput exceeds "
                    f"{device_kind} bf16 peak")
    if tail_s > 0.3 * per_call_s * n_calls + 1.0:
        sus.append(f"cycle: device_get sync tail {tail_s:.2f}s after a "
                   f"{per_call_s * n_calls:.2f}s timed loop — early acks")
    if sus:
        out["suspect"] = sus
    return out


def build_tick_probe(records: list) -> dict:
    """stats.jsonl tick records → overlap-evidence dict (pure,
    unit-testable — tests/test_bench_artifacts.py).

    Extracts what the phase-weighted numbers cannot show: where the REAL
    tick loop's wall time went — ``timing/data_wait_frac`` and the
    per-tick ``h2d`` / ``checkpoint`` loop-thread self-times that the
    ISSUE 2 overlap layer (device prefetch + async writeback) is supposed
    to have collapsed.  A checkpoint phase appears on the tick AFTER the
    boundary that saved, so multi-tick records are summarized with max."""
    ticks = [r for r in records if "timing/sec_per_tick" in r]
    if not ticks:
        return {"error": "no tick records"}
    last = ticks[-1]
    out = {
        "ticks": len(ticks),
        "sec_per_tick": round(last["timing/sec_per_tick"], 3),
        "img_per_sec_per_chip": round(
            last.get("timing/img_per_sec_per_chip", 0.0), 2),
        "data_wait_frac": round(last.get("timing/data_wait_frac", 0.0), 5),
        "phase_self_ms": {
            k.rsplit("/", 1)[-1]: round(v * 1e3, 2)
            for k, v in last.items() if k.startswith("timing/phase/")},
    }
    for name in ("h2d", "checkpoint"):
        vals = [r[f"timing/phase/{name}"] for r in ticks
                if f"timing/phase/{name}" in r]
        if vals:
            out[f"{name}_self_ms_max"] = round(max(vals) * 1e3, 2)
    return out


class _BenchSession:
    """Mutable bench state + the measurement stages (VERDICT r4 weak #4:
    one ~570-line closure became stages with seams).  Artifact CONTENT is
    built by the pure module-level builders; this class owns the device
    work (compile, time, fetch) and the run bookkeeping (best result,
    OOM notes, witness refs, incremental emission)."""

    def __init__(self, cfg, env, *, metric: str, on_tpu: bool,
                 iters: int, peak, identity: dict, profile_dir):
        import jax

        self.cfg = cfg
        self.env = env
        self.metric = metric
        self.on_tpu = on_tpu
        self.iters = iters
        self.peak = peak
        self.identity = identity
        self.profile_dir = profile_dir
        self.n_chips = len(jax.devices())
        self.platform = jax.devices()[0].platform
        self.device_kind = jax.devices()[0].device_kind
        self.res = cfg.model.resolution
        self.rng = jax.random.PRNGKey(1)
        self.t = cfg.train

        self.best = 0.0        # best emitted img/s/chip (any method)
        self.last_out: dict = {}   # last emitted JSON (sweep annotation)
        self.sweep_notes: list = []  # OOM history; survives later emits
        self.tick_probe = None  # overlap-evidence dict; rides every emit
        self.phase_results: dict = {}  # global batch -> (timings, flops)
        self.witness_refs: dict = {}   # global batch -> (d compiled, args)
        #   — keyed by batch so the traced program always matches the
        #   batch of the artifact it annotates
        self.cycle_oom_bsz = None  # smallest global batch whose CYCLE OOMed
        self.state = self.fresh_state()

    def fresh_state(self):
        """jit the whole init: ONE compiled program instead of hundreds of
        small eager dispatches (each a round-trip over the axon TPU
        tunnel).  Also the recovery path after an OOM: the step fns donate
        the state buffers, so a failed measure() leaves the old ``state``
        pointing at deleted arrays."""
        import jax

        from gansformer_tpu.train.state import create_train_state

        t_init = time.time()
        st = jax.jit(lambda k: create_train_state(self.cfg, k))(
            jax.random.PRNGKey(0))
        jax.block_until_ready(st.step)
        _log(f"state init in {time.time() - t_init:.1f}s")
        return jax.device_put(st, self.env.replicated())

    def emit_json(self, out: dict) -> None:
        """THE artifact-emission path (stdout line + phases file +
        last_out) — shared by the phase-weighted and fused-cycle
        emitters."""
        if self.sweep_notes:
            out["sweep_stopped"] = list(self.sweep_notes)
        if self.tick_probe is not None:
            out["tick_probe"] = self.tick_probe
        if os.environ.get("GRAFT_BENCH_TRACE", "0") == "1":
            # Trace mode pins each linearity-probed d executable (and its
            # donated-arg HBM buffers) for the witness — a sweep OOM under
            # this flag may not reproduce untraced; make it attributable.
            out["trace_mode"] = True
        if "phase_ms" in out:
            comms = _load_comms_payload()
            if comms is not None:
                scal = build_expected_scaling(comms, out["phase_ms"])
                if scal is not None:
                    out["expected_scaling"] = scal
        hbm = _hbm_snapshot()
        if hbm is not None:
            out["hbm"] = hbm
        self.last_out.clear()
        self.last_out.update(out)
        print(json.dumps(out), flush=True)
        try:
            with open(_PHASES_OUT, "w") as f:
                json.dump(out, f, indent=2)
        except OSError:
            pass

    def note_oom(self, msg: str) -> None:
        """Append (never overwrite) the OOM record in the final artifact."""
        self.sweep_notes.append(msg)
        if self.last_out:
            self.last_out["sweep_stopped"] = list(self.sweep_notes)
            print(json.dumps(self.last_out), flush=True)

    def _phase_fns(self, bsz: int):
        import dataclasses

        from gansformer_tpu.train.steps import make_train_steps

        b_cfg = dataclasses.replace(
            self.cfg,
            train=dataclasses.replace(self.cfg.train, batch_size=bsz))
        return make_train_steps(b_cfg, self.env, batch_size=bsz)

    def measure(self, bsz: int, emit_only_if_better: bool) -> float:
        """Compile+time the 4 lazy-reg phase variants at one global batch;
        emits JSON lines (the outer process takes the LAST parseable one,
        so emitting only-on-improvement keeps the best config's number)."""
        import jax
        import numpy as np

        from gansformer_tpu.utils.benchcheck import cadence_weighted

        fns = self._phase_fns(bsz)
        imgs = jax.device_put(
            np.random.RandomState(0).randint(
                0, 255, (bsz, self.res, self.res, 3), dtype=np.uint8),
            self.env.batch())
        # Phase plan: steady-state pair first so a partial result exists
        # as early as possible; reg variants (second-order grads, the
        # compile hogs) after.
        phases = [
            ("d", fns.d_step, (imgs, self.rng)),
            ("g", fns.g_step, (self.rng,)),
            ("d_r1", fns.d_step_r1, (imgs, self.rng)),
            ("g_pl", fns.g_step_pl, (self.rng,)),
        ]
        timings: dict = {}    # per-it wall to block_until_ready (reported)
        fetch_s: dict = {}    # post-block sync tail of a real device_get
        compile_s: dict = {}
        flops: dict = {}      # PER-DEVICE FLOPs per phase (see flops_of)
        linearity: dict = {}  # per-it time at N vs 2N iterations

        def per_chip_now() -> float:
            return bsz / cadence_weighted(
                timings, self.t.d_reg_interval,
                self.t.g_reg_interval) / self.n_chips

        def emit(partial: bool) -> None:
            per_chip = per_chip_now()
            if emit_only_if_better and partial:
                # The partial estimate approximates the (slower) reg
                # variants with the plain steps, so it is systematically
                # HIGH — emitting it in sweep mode could make an inflated
                # number from a worse config the final reported line.
                return
            if emit_only_if_better and per_chip <= self.best:
                _log(f"batch {bsz // self.n_chips}/chip: {per_chip:.1f} "
                     f"img/s — not better than {self.best:.1f}, "
                     f"not emitting")
                return
            self.emit_json(build_phase_artifact(
                metric=self.metric, on_tpu=self.on_tpu,
                n_chips=self.n_chips, platform=self.platform, bsz=bsz,
                timings=timings, flops=flops, fetch_s=fetch_s,
                compile_s=compile_s, identity=self.identity,
                peak=self.peak, d_reg_interval=self.t.d_reg_interval,
                g_reg_interval=self.t.g_reg_interval, iters=self.iters,
                linearity=linearity, device_kind=self.device_kind,
                partial=partial))

        st = self.state
        # Ambient mesh for the compiles AND the timed calls: the in-step
        # latent sharding (ISSUE 7) resolves against it — without it a
        # multi-chip bench would measure the replicated-z program the
        # real loop (which runs under env.activate()) never dispatches.
        with self.env.activate():
            return self._measure_phases(bsz, phases, st, timings, fetch_s,
                                        compile_s, flops, linearity, emit)

    def _measure_phases(self, bsz, phases, st, timings, fetch_s,
                        compile_s, flops, linearity, emit) -> float:
        import jax
        import numpy as np

        from gansformer_tpu.utils.benchcheck import (
            cadence_weighted, flops_of as _flops_of)

        def per_chip_now() -> float:
            return bsz / cadence_weighted(
                timings, self.t.d_reg_interval,
                self.t.g_reg_interval) / self.n_chips

        for name, fn, extra in phases:
            tc = time.time()
            compiled = fn.lower(st, *extra).compile()
            compile_s[name] = time.time() - tc
            fl = _flops_of(compiled)
            if fl:
                flops[name] = fl
            _log(f"[b{bsz}] compiled {name} in {compile_s[name]:.1f}s"
                 + (f" ({fl / 1e12:.3f} TFLOP/call)" if fl else ""))
            # warm-up call (also replaces donated state)
            st, _ = compiled(st, *extra)
            jax.block_until_ready(st.step)

            def timed(n_it):
                """(per-it s, post-block sync tail s) via the shared
                validated loop (``steady_state_time``, module level —
                also the satellite benches' timer): the donated state
                chains through the carry, the tail fetch reads a loss
                scalar data-dependent on the final step (checked in
                build_phase_artifact)."""
                nonlocal st
                st, per_it, tail = steady_state_time(
                    lambda carry: compiled(carry, *extra), st, n_it)
                return per_it, tail

            timings[name], fetch_s[name] = timed(self.iters)
            _log(f"[b{bsz}] timed {name}: {timings[name] * 1e3:.1f} ms/step "
                 f"(sync tail {fetch_s[name] * 1e3:.0f} ms)")
            if name == "d" and self.on_tpu:
                # Linearity probe: per-it time must hold at doubled work.
                per_it_2n, _ = timed(2 * self.iters)
                linearity[name] = (timings[name], per_it_2n)
                _log(f"[b{bsz}] linearity d: {per_it_2n * 1e3:.1f} ms/step "
                     f"at 2x iters")
                if os.environ.get("GRAFT_BENCH_TRACE", "0") == "1":
                    # Only when the witness will actually run: the stored
                    # executable pins its donated-arg image buffers in HBM
                    # for the rest of the process.
                    self.witness_refs[bsz] = (compiled, extra)
            if name == "g":
                emit(partial=True)
        self.state = st
        emit(partial=False)
        self.phase_results[bsz] = (dict(timings), dict(flops))
        return per_chip_now()

    def measure_cycle(self, bsz: int) -> None:
        """Time the FUSED lazy-reg cycle (TrainStepFns.cycle — the whole
        16-iteration hot loop as ONE program, the loop's --fused-cycle
        mode): same per-iteration work as the phase-weighted number but
        1 host dispatch per cycle instead of 32, so it bounds dispatch/
        relay overhead from above.  TPU only; invoked via ``try_cycle``
        BEFORE the sweep at the default batch (the tunnel-overhead
        datapoint must not queue behind the optional sweep) and again
        after it if the sweep finds a better batch.  Emits a better final
        line only if it beats the emitted best and passes validation.

        FLOPs note: XLA cost analysis counts a ``lax.scan`` body ONCE,
        not × trip count (verified empirically — a scanned matmul chain
        reports 1/8 of its unrolled FLOPs), so the cycle program's own
        cost analysis undercounts ~5×.  The cycle's true per-call FLOPs
        are derived from the four PHASE measurements at the same batch:
        cadence-weighted per-iteration FLOPs × cycle length."""
        import jax
        import numpy as np

        fns = self._phase_fns(bsz)
        if fns.cycle is None:
            return
        k_cyc = fns.cycle_len
        imgs_k = jax.device_put(
            np.random.RandomState(0).randint(
                0, 255, (k_cyc, bsz, self.res, self.res, 3), dtype=np.uint8),
            self.env.batch_stack())
        with self.env.activate():
            self._measure_cycle_on_mesh(bsz, fns, k_cyc, imgs_k)

    def _measure_cycle_on_mesh(self, bsz, fns, k_cyc, imgs_k) -> None:
        import jax
        import numpy as np

        from gansformer_tpu.utils.benchcheck import cadence_weighted

        tc = time.time()
        compiled = fns.cycle.lower(self.state, imgs_k, self.rng, 0).compile()
        c_s = time.time() - tc
        _, ph_flops = self.phase_results.get(bsz, ({}, {}))
        fl = (cadence_weighted(ph_flops, self.t.d_reg_interval,
                               self.t.g_reg_interval) * k_cyc
              if all(k in ph_flops for k in ("d", "g", "d_r1", "g_pl"))
              else None)
        _log(f"[b{bsz}] compiled cycle{k_cyc} in {c_s:.1f}s"
             + (f" ({fl / 1e12:.3f} TFLOP/call from phase analysis)"
                if fl else ""))
        st, sums = compiled(self.state, imgs_k, self.rng, 0)   # warm-up
        jax.block_until_ready(st.step)
        n_calls = max(2, self.iters // k_cyc * 2)
        t0 = time.time()
        for _ in range(n_calls):
            st, sums = compiled(st, imgs_k, self.rng, 0)
        jax.block_until_ready(st.step)
        t_block = time.time()
        float(np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(sums)[0])).ravel()[0])
        tail = time.time() - t_block
        self.state = st
        per_call = (t_block - t0) / n_calls
        per_chip = bsz * k_cyc / per_call / self.n_chips
        _log(f"[b{bsz}] timed cycle{k_cyc}: {per_call * 1e3:.1f} ms/cycle "
             f"= {per_chip:.1f} img/s/chip (sync tail {tail * 1e3:.0f} ms)")
        out = build_cycle_artifact(
            metric=self.metric, n_chips=self.n_chips, platform=self.platform,
            bsz=bsz, k_cyc=k_cyc, per_call_s=per_call, tail_s=tail,
            n_calls=n_calls, compile_s=c_s, identity=self.identity,
            peak=self.peak, cycle_flops=fl, device_kind=self.device_kind)
        if per_chip > self.best and "suspect" not in out:
            self.best = per_chip
            self.emit_json(out)
        else:
            _log(f"cycle{k_cyc}: {per_chip:.1f} img/s/chip — not better "
                 f"than {self.best:.1f} (or suspect), not emitting")

    def try_cycle(self, bsz: int, label: str, budget: float) -> None:
        """measure_cycle as a best-effort extra: an OOM or any other
        cycle-only failure is recorded in the artifact and must never
        cost the remaining measurements (the cycle program is a scan
        the four phase programs don't exercise — a lowering bug there
        should not kill the sweep)."""
        if self.cycle_oom_bsz is not None and bsz >= self.cycle_oom_bsz:
            _log(f"cycle: skipping batch {bsz // self.n_chips}/chip "
                 f"(>= known cycle OOM at "
                 f"{self.cycle_oom_bsz // self.n_chips}/chip)")
            return
        if time.time() - _T0 > budget - 180:
            _log(f"cycle ({label}): skipping (outer budget nearly spent)")
            return
        try:
            self.measure_cycle(bsz)
        except Exception as e:
            if _is_oom(e):
                self.cycle_oom_bsz = min(bsz, self.cycle_oom_bsz or bsz)
                self.note_oom(f"cycle oom at batch {bsz // self.n_chips}"
                              f"/chip ({label}; stacked input adds "
                              f"{self.cfg.train.d_reg_interval}x batch "
                              f"of uint8)")
            else:
                _log(f"cycle ({label}) failed (non-fatal): "
                     f"{type(e).__name__}: {str(e)[:300]}")
                self.sweep_notes.append(
                    f"cycle failed at batch {bsz // self.n_chips}/chip: "
                    f"{type(e).__name__}")
            self.state = self.fresh_state()   # buffers were donated & lost

    def run_tick_probe(self, budget: float) -> None:
        """Short REAL tick loop (train/loop.py, synthetic data) after the
        phase timing: embeds ``timing/data_wait_frac`` and the per-tick
        ``h2d`` / ``checkpoint`` loop-thread self-times in the bench JSON,
        so the overlap layer's wins (ISSUE 2: device prefetch + async
        writeback) show up in ``BENCH_r*.json``, not just in a run dir's
        stats.jsonl.  Micro synthetic config — the probe measures the
        LOOP's host-side behavior, not model throughput (the phase
        artifact already covers that).  On CPU this runs FIRST (the reg
        variants are the budget hogs there; the probe result then rides
        every later emit); on TPU it runs after the sweep.  Best-effort:
        budget-guarded and never fatal to an already-emitted result."""
        if time.time() - _T0 > budget - 150:
            _log("tick probe: skipping (outer budget nearly spent)")
            return
        import shutil
        import tempfile

        from gansformer_tpu.core.config import (
            DataConfig, ExperimentConfig, MeshConfig, ModelConfig,
            TrainConfig)
        from gansformer_tpu.train.loop import train

        # batch: divisible by the data axis (= n_chips) AND by the
        # mbstd group (4); 8 covers the 1/2/4/8-device meshes.
        bsz = 8 if 8 % self.n_chips == 0 else 4 * self.n_chips
        probe_cfg = ExperimentConfig(
            name="tickprobe",
            model=ModelConfig(resolution=16, components=2, latent_dim=16,
                              w_dim=16, mapping_dim=16, mapping_layers=2,
                              fmap_base=64, fmap_max=32,
                              attention="simplex", attn_start_res=8,
                              attn_max_res=8, mbstd_group_size=4),
            # device_time_ticks=0: the probe measures the LOOP's
            # host-side overlap behavior — a traced tick would inflate
            # exactly the data_wait/h2d evidence it exists to capture
            # (and pay the profiler's one-time init inside the budget)
            train=TrainConfig(batch_size=bsz, total_kimg=2,
                              kimg_per_tick=1, d_reg_interval=2,
                              g_reg_interval=2, pl_batch_shrink=2,
                              ema_kimg=0.01, snapshot_ticks=1,
                              image_snapshot_ticks=0, metric_ticks=0,
                              device_time_ticks=0),
            data=DataConfig(resolution=16, source="synthetic"),
            mesh=MeshConfig())
        d = tempfile.mkdtemp(prefix="graft_tick_probe_")
        try:
            _log(f"tick probe: 2-tick real loop at batch {bsz} "
                 f"(device prefetch + async writeback ON)")
            train(probe_cfg, d)
            records = [json.loads(ln)
                       for ln in open(os.path.join(d, "stats.jsonl"))]
            probe = build_tick_probe(records)
            probe["overlap"] = {
                "device_prefetch": probe_cfg.data.device_prefetch,
                "async_checkpoint": probe_cfg.train.async_checkpoint}
            self.tick_probe = probe
            if self.last_out:       # re-emit with the probe attached
                self.emit_json(dict(self.last_out))
            _log(f"tick probe: data_wait_frac="
                 f"{probe.get('data_wait_frac')} "
                 f"h2d_max={probe.get('h2d_self_ms_max')}ms "
                 f"ckpt_max={probe.get('checkpoint_self_ms_max')}ms")
        except Exception as e:
            _log(f"tick probe failed (non-fatal): "
                 f"{type(e).__name__}: {str(e)[:300]}")
        finally:
            shutil.rmtree(d, ignore_errors=True)

    def run_witness(self) -> None:
        """Device-time witness (VERDICT r3 item 1b): trace a short window
        of the ``d`` phase; the xplane's DEVICE plane records what the
        chip actually executed — relay acks cannot fake it.  Runs LAST,
        after every measurement is already emitted:
        ``jax.profiler.start_trace`` was observed to HANG forever over the
        axon tunnel (r4, 2026-07-31 — an 1800s budget died inside the
        tracer before any JSON was emitted), and incremental emission
        means a hang here costs nothing but the witness itself.  On
        success the final artifact is re-emitted with ``device_trace``
        attached (plus a ``suspect`` entry if the device time contradicts
        the claimed wall).

        OPT-IN (GRAFT_BENCH_TRACE=1): the tracer hang is not just a lost
        budget — the client killed mid-trace left the tunnel's backend
        claim WEDGED for every subsequent process for 20+ minutes (r4,
        observed).  A witness that can poison the shared backend must not
        run unattended; the sync-tail fetch + linearity probe remain the
        always-on device-time evidence (VERDICT r3 item 1b's "at minimum"
        clause)."""
        import jax

        if (not self.on_tpu or self.profile_dir or not self.witness_refs
                or not self.last_out
                or os.environ.get("GRAFT_BENCH_TRACE", "0") != "1"):
            return
        # Trace the d program of the BATCH THE FINAL ARTIFACT REPORTS, so
        # the attached evidence always describes the measured config (the
        # fused-cycle line runs at the best phase-weighted batch, so the
        # same program matches it too).
        bsz = int(self.last_out.get("batch_per_chip", 0)) * self.n_chips
        if bsz not in self.witness_refs:
            _log(f"trace witness: no d program kept for batch "
                 f"{bsz // max(self.n_chips, 1)}/chip — skipping")
            return
        import shutil
        import tempfile

        from gansformer_tpu.utils.benchcheck import trace_suspect
        from gansformer_tpu.utils.profparse import device_busy_span

        compiled, extra = self.witness_refs[bsz]
        t_d = self.phase_results.get(bsz, ({}, {}))[0].get("d", 0.0)
        tdir = tempfile.mkdtemp(prefix="graft_bench_trace_")
        n_tr = min(10, self.iters)
        st = self.state
        try:
            _log("trace witness: starting profiler "
                 "(opt-in; runs last — a tunnel hang here cannot cost "
                 "any already-emitted result)")
            jax.profiler.start_trace(tdir)
            try:
                t0_tr = time.time()
                for _ in range(n_tr):
                    st, _ = compiled(st, *extra)
                jax.block_until_ready(st.step)
                wall_tr = time.time() - t0_tr
            finally:
                jax.profiler.stop_trace()
            self.state = st
            dev = device_busy_span(tdir)
            if not dev:
                _log("trace witness: no parseable device plane (non-fatal)")
                return
            busy, span, plane = dev
            tc = {"busy_s": round(busy, 4), "span_s": round(span, 4),
                  "wall_s": round(wall_tr, 4), "iters": n_tr, "plane": plane}
            _log(f"trace witness: device busy {busy * 1e3:.1f} ms over "
                 f"{n_tr} iters (wall {wall_tr * 1e3:.1f} ms, plane {plane})")
            if self.last_out:
                out = dict(self.last_out)
                out["device_trace"] = tc
                # device_ms next to the wall phase_ms (ISSUE 8): the
                # witness traced n_tr iterations of the d program, so
                # busy/n_tr is the per-iteration DEVICE time for that
                # phase — the number the wall clock must answer to.
                if busy > 0:
                    attach_device_ms(
                        out, {"d": busy / n_tr * 1e3},
                        self.phase_results.get(bsz, ({}, {}))[1],
                        self.peak)
                ts = trace_suspect(busy, wall_tr, n_tr, t_d)
                if ts:
                    out["suspect"] = out.get("suspect", []) + [ts]
                self.emit_json(out)
        except Exception as e:
            _log(f"trace witness failed (non-fatal): "
                 f"{type(e).__name__}: {str(e)[:200]}")
        finally:
            shutil.rmtree(tdir, ignore_errors=True)


def _device_identity() -> dict:
    """Device identity evidence (VERDICT r3 item 1c): enough to answer
    "was this really N chips of kind K?" from the artifact alone."""
    import jax

    dev0 = jax.devices()[0]
    identity = {
        "device_kind": dev0.device_kind,
        "platform": dev0.platform,
        "n_devices": len(jax.devices()),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
    }
    try:
        mstats = dev0.memory_stats() or {}
        identity["memory_stats"] = {
            k: int(mstats[k]) for k in
            ("bytes_in_use", "bytes_limit", "peak_bytes_in_use")
            if k in mstats}
    except Exception:
        pass
    return identity


def _run_inner() -> None:
    """The benchmark driver: backend/config setup, then the stage plan —
    default-batch measure (OOM-halving once), pre-sweep fused cycle,
    batch sweep, post-sweep cycle, opt-in trace witness.  Emits progress
    on stderr and one-or-more JSON lines on stdout (the last one wins)."""
    import dataclasses

    import jax

    # Persistent compilation cache: the single biggest fix for the r1/r2
    # "TPU bench never finishes compiling" failure.  Must be set before the
    # first compile; ONE definition shared with the CLI entry points so
    # bench and training warm-start each other's compiles.
    sys.path.insert(0, _REPO)
    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache(_REPO)

    from gansformer_tpu.core.config import get_preset
    from gansformer_tpu.parallel.mesh import make_mesh
    from gansformer_tpu.utils.benchcheck import peak_tflops

    n_chips = len(jax.devices())
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    _log(f"backend up: {n_chips}x {jax.devices()[0].device_kind} ({platform})")

    cfg = get_preset("ffhq256-duplex")
    # GRAFT_BENCH_BATCH sweeps per-chip batch (PERF.md §1b); default 8
    # matches the flagship preset's per-chip share.
    per_chip = int(os.environ.get("GRAFT_BENCH_BATCH", "8"))
    batch = (per_chip * n_chips) if on_tpu else max(4, n_chips)
    if not on_tpu:
        # CPU fallback so the bench always emits a line: tiny proxy config.
        cfg = get_preset("clevr64-simplex")
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, dtype="float32"))
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, batch_size=batch))
    metric = ("train_img_per_sec_per_chip_ffhq256_duplex" if on_tpu
              else "train_img_per_sec_per_chip_cpu_proxy")

    profile_dir = os.environ.get("GRAFT_BENCH_PROFILE")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    dev0 = jax.devices()[0]
    identity = _device_identity()

    sess = _BenchSession(
        cfg, make_mesh(cfg.mesh), metric=metric, on_tpu=on_tpu,
        iters=20 if on_tpu else 3,
        peak=peak_tflops(dev0.device_kind) if on_tpu else None,
        identity=identity, profile_dir=profile_dir)

    best_phase = 0.0    # best PHASE-WEIGHTED result (sweep tracking — the
    #                     cycle number must not hide a better batch)
    best_bsz = 0        # global batch of the best phase-weighted result
    oom_per_chip = None  # smallest per-chip batch known to OOM

    probe_on = os.environ.get("GRAFT_BENCH_TICKPROBE", "1") != "0"
    cycle_on = (on_tpu and
                os.environ.get("GRAFT_BENCH_CYCLE", "1") != "0")
    budget = (float(os.environ.get("GRAFT_BENCH_TPU_TIMEOUT", "900"))
              if on_tpu else _cpu_budget())
    try:
        if probe_on and not on_tpu:
            # CPU: the reg-variant compiles below are the budget hogs —
            # take the overlap evidence FIRST; it rides every later emit.
            sess.run_tick_probe(budget)
        try:
            sess.best = best_phase = sess.measure(
                batch, emit_only_if_better=False)
            best_bsz = batch
        except Exception as e:
            # OOM at the default batch: halve once instead of dying with
            # the budget spent (VERDICT r3 weak #4).
            if not (on_tpu and _is_oom(e)):
                raise
            oom_per_chip = batch // n_chips
            # halve PER-CHIP (global stays divisible by the data axis)
            half = max(1, oom_per_chip // 2) * n_chips
            if half == batch:
                raise               # already at 1/chip — nothing to shrink
            _log(f"OOM at batch {oom_per_chip}/chip; retrying at half")
            batch = half
            # The failed measure() donated the old state's buffers into the
            # aborted execution — rebuild before retrying.
            sess.state = sess.fresh_state()
            sess.best = best_phase = sess.measure(
                batch, emit_only_if_better=False)
            best_bsz = batch
            sess.note_oom(f"oom at default batch {oom_per_chip}/chip; "
                          f"fell back to {batch // n_chips}/chip")

        # Fused-cycle at the default batch FIRST (before the compile-heavy
        # sweep): one dispatch per 16 iterations is the number that shows
        # whether per-dispatch tunnel overhead caps the phase-weighted
        # result, and tunnel windows have died mid-sweep before (r4) — the
        # most informative datapoint must not queue behind the optional one.
        if cycle_on and best_bsz:
            sess.try_cycle(best_bsz, "pre-sweep", budget)

        # Batch sweep (TPU only): larger per-chip batches usually feed the
        # MXU better; try each while the outer budget allows, emitting only
        # improvements so the final JSON line is the best measured config.
        if on_tpu:
            sweep = os.environ.get("GRAFT_BENCH_SWEEP", "16,32")
            for per_chip_b in [int(s) for s in sweep.split(",") if s.strip()]:
                if per_chip_b * n_chips == batch:
                    continue
                if oom_per_chip is not None and per_chip_b >= oom_per_chip:
                    # don't pay minutes of compile for a guaranteed OOM
                    _log(f"sweep: skipping batch {per_chip_b}/chip "
                         f"(>= known OOM at {oom_per_chip}/chip)")
                    continue
                if time.time() - _T0 > budget - 240:
                    _log(f"sweep: skipping batch {per_chip_b}/chip "
                         f"(outer budget nearly spent)")
                    break
                try:
                    r = sess.measure(per_chip_b * n_chips,
                                     emit_only_if_better=True)
                    if r > best_phase:
                        best_phase, best_bsz = r, per_chip_b * n_chips
                    sess.best = max(sess.best, r)
                except Exception as e:
                    if not _is_oom(e):
                        raise
                    # Record the stop in the FINAL artifact instead of
                    # dying silently after the budget is spent.
                    oom_per_chip = min(per_chip_b, oom_per_chip or per_chip_b)
                    _log(f"sweep: OOM at batch {per_chip_b}/chip")
                    if sess.last_out:
                        sess.note_oom(f"oom at batch {per_chip_b}/chip")
                    sess.state = sess.fresh_state()  # buffers donated & lost

        # Re-measure the fused cycle at the sweep's winning batch when the
        # sweep found a better config than the pre-sweep cycle already
        # covered (cycle FLOPs derive from that batch's phase analyses).
        # GRAFT_BENCH_CYCLE=0 skips both cycle measurements; CPU always
        # skips (one cycle call costs ~16 proxy iterations and would blow
        # the 270s fallback budget).
        if cycle_on and best_bsz and best_bsz != batch:
            sess.try_cycle(best_bsz, "post-sweep", budget)

        # Real tick-loop probe (TPU: after the sweep): the overlap
        # layer's data_wait_frac / h2d / checkpoint evidence rides in
        # the final artifact.
        if probe_on and on_tpu:
            sess.run_tick_probe(budget)

        # Absolute last: the profiler witness (can hang over the tunnel).
        sess.run_witness()
    finally:
        if profile_dir:
            jax.profiler.stop_trace()


def _cpu_budget() -> float:
    """CPU-fallback child budget.  420s (raised from 270 with the tick
    probe's arrival): probe ≈110s warm + the d/g phase compiles+timing;
    the reg variants may still overrun, which the incremental-emission
    design already tolerates (the partial line is labeled)."""
    return float(os.environ.get("GRAFT_BENCH_CPU_TIMEOUT", "420"))


def _probe_tpu(timeout: float = 90.0) -> bool:
    """Cheap child that just initializes the ambient backend. Returns True
    iff a TPU platform comes up within the timeout (a wedged tunnel claim
    hangs forever — don't let the full bench budget pay for that)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and "tpu" in (proc.stdout or "")


def _attempt(env: dict, timeout: float):
    """Run the inner bench in a child; return (parsed JSON dict | None, err).

    Takes the LAST parseable JSON line — the inner emits incrementally, so
    even a timed-out child can yield a (partial) result."""
    env = dict(env)
    env[_INNER_FLAG] = "1"
    stdout, err = "", None
    try:
        proc = subprocess.run(
            [sys.executable, _SELF], env=env, cwd=_REPO,
            capture_output=True, text=True, timeout=timeout)
        stdout = proc.stdout or ""
        if proc.returncode != 0:
            err = (proc.stderr or "")[-2000:]
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout or ""
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        stderr_tail = e.stderr or ""
        if isinstance(stderr_tail, bytes):
            stderr_tail = stderr_tail.decode("utf-8", "replace")
        err = f"timeout after {timeout:.0f}s; progress: {stderr_tail[-1200:]}"
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            if err and "partial" in result:
                result["note"] = err[:500]
            return result, None
    return None, err or f"no JSON line in output: {stdout[-500:]!r}"


def main() -> None:
    if os.environ.get(_INNER_FLAG) == "1":
        if os.environ.get(_SCALING_FLAG) == "1":
            _run_scaling_inner()
        else:
            _run_inner()
        return
    if "--scaling" in sys.argv[1:]:
        _run_scaling_outer()
        return

    sys.path.insert(0, _REPO)
    from gansformer_tpu.utils.hostenv import sanitized_cpu_env

    # Cold compile of the reg variants was measured at ~11 min on the v5e
    # tunnel; warm (persistent cache) is under a minute.  The budget must
    # survive cold compile (VERDICT r2) — and thanks to incremental
    # emission even an overrun yields the steady-state TPU number.
    tpu_budget = float(os.environ.get("GRAFT_BENCH_TPU_TIMEOUT", "900"))
    tpu_err = None
    if _probe_tpu():
        result, tpu_err = _attempt(dict(os.environ), tpu_budget)
        if result is not None:
            print(json.dumps(result))
            return
    else:
        tpu_err = "TPU probe failed: backend did not come up within 90s"
    # sanitized CPU: PYTHONPATH cleared so the TPU sitecustomize can't
    # claim/hang the tunnel; proxy config keeps runtime small.
    result, cpu_err = _attempt(sanitized_cpu_env(1), _cpu_budget())
    if result is not None:
        if tpu_err:
            result["tpu_error"] = tpu_err[:1000]
        print(json.dumps(result))
        return
    print(json.dumps({
        "metric": "train_img_per_sec_per_chip_ffhq256_duplex",
        "value": 0.0,
        "unit": "img/sec/chip",
        "vs_baseline": 0.0,
        "error": f"tpu: {tpu_err}; cpu: {cpu_err}"[:1500],
    }))


if __name__ == "__main__":
    main()
